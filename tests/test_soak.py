"""Chaos soak harness: live fault injection on the UDP runtime + the
linearizability cross-check of recorded histories.

Tier-1 keeps a fast deterministic smoke (loopback, a few hundred ops,
seconds-scale); the acceptance-criteria grids (≥2k client ops under
loss + duplication + partition + repeated crash–restart, write-once AND
ABD) ride under ``-m slow``. The "volatile caught" twin — the live
analog of ``write_once_packed.py``'s buggy variant — must be rejected
by the cross-check and dump a reproducible seed artifact, which
``tests/test_fuzz_differential.py`` replays from the committed
``soak_seeds/`` corpus.
"""

import os
import pickle
import socket
import sys
import threading
import time

import pytest

from stateright_tpu.actor import ChaosNetwork, Id, spawn
from stateright_tpu.actor.core import Actor, Out
from stateright_tpu.actor.runtime import cluster_rng
from stateright_tpu.obs import Metrics, validate_event
from stateright_tpu.semantics import (HistoryRecorder,
                                      LinearizabilityTester,
                                      RecordedHistory, Read, ReadOk,
                                      WORegister, Write, WriteOk)

pytestmark = pytest.mark.faults

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def _soak():
    sys.path.insert(0, _TOOLS)
    try:
        import soak
    finally:
        sys.path.pop(0)
    return soak


def _free_udp_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


class _FakeSock:
    """Records sendto calls (no network); stands in for a bound UDP
    socket under the chaos layer."""

    def __init__(self):
        self.sent = []

    def sendto(self, data, addr):
        self.sent.append((data, addr))
        return len(data)


_A = Id.from_socket_addr((127, 0, 0, 1), 5001)
_B = Id.from_socket_addr((127, 0, 0, 1), 5002)
_B_ADDR = ("127.0.0.1", 5002)


class TestChaosDecisions:
    def test_seeded_loss_is_deterministic(self):
        def pattern(seed):
            net = ChaosNetwork(seed=seed, loss=0.5)
            sock = net.wrap(_A, _FakeSock())
            out = []
            for i in range(64):
                before = len(sock._sock.sent)
                sock.sendto(b"x%d" % i, _B_ADDR)
                out.append(len(sock._sock.sent) > before)
            net.close()
            return out

        assert pattern(7) == pattern(7)
        assert pattern(7) != pattern(8)

    def test_decision_stream_alignment_across_knobs(self):
        # the same seed drops the same datagram positions whether or
        # not OTHER fault knobs are enabled (all draws always happen)
        def drops(**kw):
            net = ChaosNetwork(seed=3, loss=0.4, **kw)
            sock = net.wrap(_A, _FakeSock())
            out = []
            for i in range(64):
                net.metrics.set("dropped", 0)
                sock.sendto(b"y", _B_ADDR)
                out.append(net.metrics.get("dropped", 0) > 0)
            net.close()
            return out

        assert drops() == drops(delay=0.9, delay_range=(0.0, 0.001))

    def test_partition_blocks_cross_group_links(self):
        net = ChaosNetwork(seed=0)
        fake = _FakeSock()
        sock = net.wrap(_A, fake)
        net.set_partition([[int(_A)], [int(_B)]])
        assert not net.allows(_A, _B)
        sock.sendto(b"blocked", _B_ADDR)
        assert fake.sent == []
        assert net.metrics.get("dropped") == 1
        assert net.metrics.get("partitions") == 1
        # unlisted ids are wildcards; healing restores the link
        other = Id.from_socket_addr((127, 0, 0, 1), 5003)
        assert net.allows(_A, other) and net.allows(other, _B)
        net.heal()
        sock.sendto(b"flows", _B_ADDR)
        assert len(fake.sent) == 1
        net.close()

    def test_duplicate_and_delay_deliver_everything(self):
        net = ChaosNetwork(seed=1, duplicate=1.0, delay=1.0,
                           delay_range=(0.0, 0.001))
        fake = _FakeSock()
        sock = net.wrap(_A, fake)
        for i in range(10):
            sock.sendto(b"d%d" % i, _B_ADDR)
        deadline = time.monotonic() + 2.0
        while len(fake.sent) < 20 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(fake.sent) == 20  # 10 delayed + 10 duplicates
        assert net.metrics.get("duplicated") == 10
        assert net.metrics.get("delayed") == 10
        net.close()

    def test_per_link_override(self):
        net = ChaosNetwork(seed=2, loss=0.0)
        net.set_link(_A, _B, loss=1.0)
        fake = _FakeSock()
        sock = net.wrap(_A, fake)
        sock.sendto(b"gone", _B_ADDR)
        assert fake.sent == []
        other = ("127.0.0.1", 5003)
        sock.sendto(b"kept", other)
        assert len(fake.sent) == 1
        net.close()


class _WOVolatile(Actor):
    """Write-once register, value in volatile memory (None=unwritten);
    messages are plain pickled tuples for the runtime tests."""

    def on_start(self, id, o):
        return None

    def on_msg(self, id, state, src, msg, o):
        kind, rid, val = msg
        if kind == "put":
            if state is None or state == val:
                o.send(src, ("put_ok", rid, None))
                return val if state is None else None
            o.send(src, ("put_fail", rid, None))
            return None
        if kind == "get":
            o.send(src, ("get_ok", rid, state))
        return None


class _WODurable(_WOVolatile):
    def durable(self, id, state):
        return state

    def on_restart(self, id, durable, o):
        return durable


def _rpc(sock, addr, msg, timeout=2.0):
    rid = msg[1]
    sock.settimeout(0.25)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sock.sendto(pickle.dumps(msg), addr)
        try:
            reply = pickle.loads(sock.recv(65535))
        except (socket.timeout, OSError):
            continue
        if reply[1] == rid:
            return reply
    raise AssertionError(f"no reply for {msg!r}")


class TestCrashRestart:
    def _cluster(self, actor):
        port = _free_udp_port()
        sid = Id.from_socket_addr((127, 0, 0, 1), port)
        handle = spawn(pickle.dumps, pickle.loads, [(sid, actor)],
                       background=True, seed=5)
        client = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        client.bind(("127.0.0.1", 0))
        return handle, sid, client, ("127.0.0.1", port)

    def test_durable_value_survives_crash_restart(self):
        handle, sid, client, addr = self._cluster(_WODurable())
        try:
            assert _rpc(client, addr, ("put", 1, "X"))[0] == "put_ok"
            durable = handle.crash(sid)
            assert durable == "X"  # the captured projection
            handle.restart(sid)
            assert _rpc(client, addr, ("get", 2, None)) \
                == ("get_ok", 2, "X")
        finally:
            handle.stop()
            client.close()

    def test_volatile_value_lost_and_cross_check_catches_it(self):
        handle, sid, client, addr = self._cluster(_WOVolatile())
        rec = HistoryRecorder()
        try:
            rec.invoke("c0", Write("X"))
            assert _rpc(client, addr, ("put", 1, "X"))[0] == "put_ok"
            rec.ret("c0", WriteOk())
            assert handle.crash(sid) is None  # fail-stop: no durable
            handle.restart(sid)
            rec.invoke("c0", Read())
            reply = _rpc(client, addr, ("get", 2, None))
            rec.ret("c0", ReadOk(reply[2]))
            assert reply == ("get_ok", 2, None)  # the write is GONE
        finally:
            handle.stop()
            client.close()
        history = rec.history()
        assert not history.check(LinearizabilityTester(WORegister()))
        # and the artifact round-trips to the same rejection
        meta, loaded = RecordedHistory.from_jsonl(
            history.to_jsonl({"spec": "woregister"}))
        assert meta == {"spec": "woregister"}
        assert not loaded.check(LinearizabilityTester(WORegister()))

    def test_crash_restart_state_machine_guards(self):
        handle, sid, client, addr = self._cluster(_WODurable())
        try:
            handle.crash(sid)
            with pytest.raises(ValueError, match="already down"):
                handle.crash(sid)
            handle.restart(sid)
            with pytest.raises(ValueError, match="not down"):
                handle.restart(sid)
        finally:
            handle.stop()
            client.close()


class _BigSender(Actor):
    """Emits an oversized datagram (EMSGSIZE) on first contact, then
    acks — the send path must log-and-ignore, not die."""

    def on_start(self, id, o):
        return 0

    def on_msg(self, id, state, src, msg, o):
        if state == 0:
            o.send(src, b"x" * 100_000)  # > UDP max: sendto raises
            o.send(src, "alive")
            return 1
        o.send(src, "alive")
        return None


class TestRuntimeSatellites:
    def test_seeded_timer_rng_is_deterministic(self):
        a = cluster_rng(42, Id(3))
        b = cluster_rng(42, Id(3))
        other = cluster_rng(42, Id(4))
        seq_a = [a.uniform(0, 1) for _ in range(8)]
        assert seq_a == [b.uniform(0, 1) for _ in range(8)]
        assert seq_a != [other.uniform(0, 1) for _ in range(8)]
        # seed=None keeps the legacy global-random behavior
        assert cluster_rng(None, Id(3)) is __import__("random")

    def test_send_oserror_does_not_kill_actor(self):
        port = _free_udp_port()
        sid = Id.from_socket_addr((127, 0, 0, 1), port)
        handle = spawn(pickle.dumps, pickle.loads,
                       [(sid, _BigSender())], background=True)
        client = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        client.bind(("127.0.0.1", 0))
        try:
            client.settimeout(0.25)
            for attempt in range(2):
                # first contact triggers the EMSGSIZE send; the actor
                # must survive it and still answer
                deadline = time.monotonic() + 2.0
                got = None
                while got is None and time.monotonic() < deadline:
                    client.sendto(pickle.dumps("ping"),
                                  ("127.0.0.1", port))
                    try:
                        got = pickle.loads(client.recv(65535))
                    except (socket.timeout, OSError):
                        continue
                assert got == "alive"
            assert handle.failures() == []
        finally:
            handle.stop()
            client.close()

    def test_socket_released_on_every_exit_path(self):
        # stop/crash close the socket in a finally: rebinding the SAME
        # port repeatedly only works if each cycle released it
        port = _free_udp_port()
        sid = Id.from_socket_addr((127, 0, 0, 1), port)
        for _ in range(6):
            handle = spawn(pickle.dumps, pickle.loads,
                           [(sid, _WODurable())], background=True)
            handle.stop()
        # crash/restart cycles rebind too
        handle = spawn(pickle.dumps, pickle.loads,
                       [(sid, _WODurable())], background=True)
        try:
            for _ in range(3):
                handle.crash(sid)
                handle.restart(sid)
            assert handle.failures() == []
        finally:
            handle.stop()


class TestSoakSmoke:
    """The tier-1 soak: a few hundred ops on loopback with every fault
    class live, finished and cross-checked in seconds."""

    def test_durable_write_once_soak_history_ok(self, tmp_path):
        soak = _soak()
        trace = []
        res = soak.run_soak(soak.SoakConfig(
            protocol="write_once", ops=220, clients=3, seed=3,
            loss=0.04, duplicate=0.04, delay=0.12, crashes=1,
            partitions=1, op_timeout=0.2, crash_down=0.05,
            partition_span=0.1, deadline=30.0, trace=trace,
            artifact_dir=str(tmp_path)))
        assert res["history_ok"] is True
        assert res["artifact"] is None
        assert res["crashes"] == 1 and res["restarts"] == 1
        assert res["partitions"] == 1
        assert res["dropped"] > 0  # seeded loss really fired
        assert res["completed"] > 150
        # obs integration: every event validates against the schema,
        # and the soak lifecycle events are all present
        for ev in trace:
            validate_event(ev)
        kinds = {e["ev"] for e in trace}
        assert {"run_start", "fault_injection", "ops", "crash",
                "restart", "partition", "soak_done"} <= kinds
        done = [e for e in trace if e["ev"] == "soak_done"][-1]
        assert done["history_ok"] is True
        assert done["engine"] == "soak"

    def test_abd_soak_smoke_history_ok(self, tmp_path):
        # quorum replication + durable (seq, val) + request dedup stay
        # linearizable under dup/loss/delay and a live crash-restart
        soak = _soak()
        res = soak.run_soak(soak.SoakConfig(
            protocol="abd", ops=300, clients=3, seed=6, loss=0.02,
            duplicate=0.02, delay=0.08, crashes=1, partitions=1,
            op_timeout=0.2, deadline=40.0,
            artifact_dir=str(tmp_path)))
        assert res["history_ok"] is True
        assert res["crashes"] == 1 and res["restarts"] == 1
        assert res["completed"] > 200

    def test_volatile_twin_is_caught_and_dumps_artifact(self, tmp_path):
        soak = _soak()
        trace = []
        res = soak.run_soak(soak.volatile_demo_config(
            artifact_dir=str(tmp_path), trace=trace))
        assert res["history_ok"] is False
        assert res["crashes"] == 1
        # the ONLINE checker flagged the violation mid-stream: the
        # offending op index is pinned strictly before the end of the
        # history (acceptance: online, not post-hoc)
        assert res["violation_op"] is not None
        assert res["violation_op"] < res["ops"]
        viol = [e for e in trace if e["ev"] == "violation"]
        assert viol and viol[0]["tester"] == "linearizability"
        assert viol[0]["op_index"] == res["violation_op"]
        path = res["artifact"]
        assert path is not None and os.path.exists(path)
        # keyed corpus layout: (protocol, tester, sha256(ops)) in the
        # filename, so a re-found identical history updates in place
        base = os.path.basename(path)
        assert base.startswith(
            "soak_write_once_volatile_linearizability_")
        # the artifact replays to the same rejection (the regression
        # contract test_fuzz_differential.py runs over the corpus)
        assert soak.check_artifact(path) == {"linearizability": False}

    def test_trace_report_renders_soak_postmortem(self, tmp_path,
                                                  capsys):
        soak = _soak()
        path = tmp_path / "soak.jsonl"
        soak.run_soak(soak.SoakConfig(
            protocol="write_once", ops=60, clients=2, seed=9,
            loss=0.0, duplicate=0.0, delay=0.0, crashes=1,
            partitions=0, op_timeout=0.2, deadline=20.0,
            trace=str(path), artifact_dir=str(tmp_path)))
        sys.path.insert(0, _TOOLS)
        try:
            import trace_report
        finally:
            sys.path.pop(0)
        assert trace_report.main([str(path), "--validate"]) == 0
        out = capsys.readouterr().out
        assert "=== engine: soak" in out
        assert "soak: ops=" in out and "history_ok=True" in out
        assert "crash" in out and "restart" in out


@pytest.mark.slow
class TestSoakGrids:
    """The acceptance-criteria grids: ≥2k client ops with loss +
    duplication + partition + ≥2 live crash–restarts, write-once AND
    ABD, deterministic seeds."""

    def test_write_once_2k_ops_full_fault_grid(self, tmp_path):
        soak = _soak()
        res = soak.run_soak(soak.SoakConfig(
            protocol="write_once", ops=2000, clients=4, seed=1,
            loss=0.03, duplicate=0.03, delay=0.1, crashes=2,
            partitions=2, op_timeout=0.25, deadline=120.0,
            testers=("linearizability", "sequential"),
            artifact_dir=str(tmp_path)))
        assert res["ops"] >= 2000
        assert res["history_ok"] is True
        assert res["testers"] == {"linearizability": True,
                                  "sequential": True}
        assert res["crashes"] == 2 and res["restarts"] == 2
        assert res["partitions"] == 2
        assert res["dropped"] > 0 and res["duplicated"] > 0

    def test_abd_2k_ops_full_fault_grid(self, tmp_path):
        soak = _soak()
        res = soak.run_soak(soak.SoakConfig(
            protocol="abd", ops=2000, clients=3, seed=2,
            loss=0.02, duplicate=0.02, delay=0.08, crashes=2,
            partitions=1, op_timeout=0.25, deadline=240.0,
            artifact_dir=str(tmp_path)))
        assert res["ops"] >= 2000
        assert res["history_ok"] is True
        assert res["crashes"] == 2 and res["restarts"] == 2

    def test_soak_cli_and_bench_soak_smoke_contract(self, tmp_path):
        import json
        import subprocess

        root = os.path.dirname(_TOOLS)
        # the CLI lands a JSON result line, rc=0 on history_ok
        proc = subprocess.run(
            [sys.executable, os.path.join(_TOOLS, "soak.py"),
             "--ops", "80", "--clients", "2", "--seed", "4",
             "--crashes", "1", "--partitions", "0",
             "--artifact-dir", str(tmp_path)],
            capture_output=True, text=True, timeout=120, cwd=root)
        assert proc.returncode == 0, proc.stderr
        line = json.loads(proc.stdout.strip().splitlines()[-1])
        assert line["history_ok"] is True
        # bench --soak-smoke: the crash-proof soak contract line
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "bench.py"),
             "--soak-smoke"],
            capture_output=True, text=True, timeout=120, cwd=root)
        assert proc.returncode == 0, proc.stderr
        contract = json.loads(proc.stdout.strip().splitlines()[-1])
        assert contract["unit"] == "ops/s"
        assert contract["history_ok"] is True
        assert contract["value"] > 0
        assert contract["faults"]["crashes"] == 1
        assert "partial" not in contract
