"""Stable-fingerprint unit tests.

Covers the invariants the reference pins for its hashing utilities
(`/root/reference/src/util.rs:202-252`, `:371-431`): insertion-order
independence for sets/maps, nested containers, and stability across runs
(fingerprints are persisted in Explorer URLs and replayed paths).
"""

from stateright_tpu.fingerprint import fp64_words, stable_fingerprint


def test_fp64_nonzero_and_stable():
    assert fp64_words([]) != 0
    assert fp64_words([1, 2, 3]) == fp64_words([1, 2, 3])
    assert fp64_words([1, 2, 3]) != fp64_words([3, 2, 1])
    assert fp64_words([0]) != fp64_words([0, 0])


def test_fp64_known_vectors():
    # Frozen golden values: guards against accidental algorithm drift, which
    # would silently break replay of previously recorded fingerprint paths.
    assert fp64_words([]) == 0xEBB6C228CB72770F
    assert fp64_words([1]) == 0xCB69997534FEF624
    assert fp64_words([0xDEADBEEF, 42]) == 0x30267343114D8791


def test_scalar_distinctions():
    assert stable_fingerprint(0) != stable_fingerprint(False)
    assert stable_fingerprint(1) != stable_fingerprint(True)
    assert stable_fingerprint("1") != stable_fingerprint(1)
    assert stable_fingerprint(b"1") != stable_fingerprint("1")
    assert stable_fingerprint(None) != stable_fingerprint(0)
    assert stable_fingerprint(-1) != stable_fingerprint(1)
    assert stable_fingerprint((1, 2)) != stable_fingerprint((2, 1))
    assert stable_fingerprint((1, 2)) != stable_fingerprint(((1, 2),))


def test_large_ints():
    assert stable_fingerprint(2**64) != stable_fingerprint(0)
    assert stable_fingerprint(2**64 + 1) != stable_fingerprint(2**64)
    assert stable_fingerprint(-(2**64)) != stable_fingerprint(2**64)


def test_set_insertion_order_independence():
    # util.rs:202-252: HashableHashSet hash ignores insertion order.
    a = frozenset([1, 2, 3, 99])
    b = frozenset([99, 3, 2, 1])
    assert stable_fingerprint(a) == stable_fingerprint(b)
    assert stable_fingerprint(a) != stable_fingerprint(frozenset([1, 2, 3]))
    # set and frozenset with equal contents hash the same
    assert stable_fingerprint({1, 2}) == stable_fingerprint(frozenset([2, 1]))


def test_nested_sets():
    # util.rs nested-set test analog.
    a = frozenset([frozenset([1, 2]), frozenset([3])])
    b = frozenset([frozenset([3]), frozenset([2, 1])])
    assert stable_fingerprint(a) == stable_fingerprint(b)


def test_map_insertion_order_independence():
    # util.rs:371-431: HashableHashMap analog.
    a = {"x": 1, "y": 2}
    b = {"y": 2, "x": 1}
    assert stable_fingerprint(a) == stable_fingerprint(b)
    assert stable_fingerprint(a) != stable_fingerprint({"x": 2, "y": 1})


def test_tuple_list_equivalence():
    # Sequences hash by content; tuple/list distinction is not meaningful
    # state (mirrors Rust where both Vec and arrays hash as sequences).
    assert stable_fingerprint([1, 2]) == stable_fingerprint((1, 2))


def test_dataclass_fingerprints():
    import dataclasses

    @dataclasses.dataclass
    class P:
        x: int
        y: int

    @dataclasses.dataclass
    class Q:
        x: int
        y: int

    assert stable_fingerprint(P(1, 2)) == stable_fingerprint(P(1, 2))
    assert stable_fingerprint(P(1, 2)) != stable_fingerprint(P(2, 1))
    # Different classes with identical fields fingerprint differently.
    assert stable_fingerprint(P(1, 2)) != stable_fingerprint(Q(1, 2))


def test_enum_fingerprints():
    import enum

    class Color(enum.Enum):
        RED = 1
        BLUE = 2

    class Shade(enum.Enum):
        RED = 1
        BLUE = 2

    assert stable_fingerprint(Color.RED) == stable_fingerprint(Color.RED)
    assert stable_fingerprint(Color.RED) != stable_fingerprint(Color.BLUE)
    assert stable_fingerprint(Color.RED) != stable_fingerprint(Shade.RED)


def test_native_hash_matches_python_reference():
    """The C core (when built) must agree with the pure-Python reference."""
    import random

    import numpy as np

    from stateright_tpu.fingerprint import (_fp64_words_py, fp64_rows,
                                            fp64_words)

    rng = random.Random(7)
    for _ in range(100):
        words = [rng.randrange(0, 2 ** 32)
                 for _ in range(rng.randrange(0, 40))]
        assert fp64_words(words) == _fp64_words_py(words)
    assert fp64_words([]) == _fp64_words_py([])
    # iterator inputs must not lose words on the masked-retry path
    assert fp64_words(iter([1, 2 ** 32])) == _fp64_words_py([1, 0])
    rows = np.array([[1, 2, 3], [4, 5, 6], [0, 0, 0]], dtype=np.uint32)
    assert fp64_rows(rows) == [_fp64_words_py(r.tolist()) for r in rows]
