"""Eventually-property semantics on the TPU engines: the DGraph pins
(`/root/reference/src/checker.rs:350-415`) — including the documented
unsoundness for cycles/DAG-rejoins (`bfs.rs:239-256`) — must hold
identically on both device modes."""

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.core import Property  # noqa: E402
from stateright_tpu.models.fixtures import PackedDGraph  # noqa: E402


def eventually_odd():
    return Property.eventually("odd", lambda _, s: s % 2 == 1)


def check_tpu(graph, mode):
    return (graph.checker()
            .tpu_options(capacity=1 << 10, mode=mode, fmax=16)
            .spawn_tpu().join())


MODES = ["device", "level"]


@pytest.mark.parametrize("mode", MODES)
class TestTpuEventually:
    def test_can_validate(self, mode):
        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([1])
             .with_path([2, 3])
             .with_path([2, 6, 7])
             .with_path([4, 9, 10]))
        check_tpu(g, mode).assert_properties()
        check_tpu(PackedDGraph.with_property(eventually_odd())
                  .with_path([2, 6, 7]), mode).assert_properties()

    def test_can_discover_counterexample(self, mode):
        c = check_tpu(PackedDGraph.with_property(eventually_odd())
                      .with_path([0, 1]).with_path([0, 2]), mode)
        assert c.discovery("odd").into_states() == [0, 2]

        c = check_tpu(PackedDGraph.with_property(eventually_odd())
                      .with_path([0, 1]).with_path([2, 4]), mode)
        assert c.discovery("odd").into_states() == [2, 4]

        c = check_tpu(PackedDGraph.with_property(eventually_odd())
                      .with_path([0, 1, 4, 6]).with_path([2, 4, 8]), mode)
        # two even terminals (6 via 4, 8 via 4); the engine reports one —
        # both witnesses are valid (the reference's multithreaded engines
        # are similarly nondeterministic)
        states = c.discovery("odd").into_states()
        assert states in ([2, 4, 6], [2, 4, 8], [0, 1, 4, 6], [0, 1, 4, 8])

    def test_fixme_can_miss_counterexample_when_revisiting_a_state(
            self, mode):
        # cycles / DAG rejoins are not treated as terminal — replicate the
        # reference's accepted unsoundness exactly (checker.rs:402-414)
        c = check_tpu(PackedDGraph.with_property(eventually_odd())
                      .with_path([0, 2, 4, 2]), mode)
        assert c.discovery("odd") is None
        c = check_tpu(PackedDGraph.with_property(eventually_odd())
                      .with_path([0, 2, 4]).with_path([1, 4, 6]), mode)
        assert c.discovery("odd") is None

    def test_differential_with_host(self, mode):
        # same graph family: device reached set == host reached set
        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 1, 4, 6]).with_path([2, 4, 8]))
        host = g.check()
        dev = check_tpu(g, mode)
        assert (dev.generated_fingerprints()
                == host.generated_fingerprints())
