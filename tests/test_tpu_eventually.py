"""Eventually-property semantics on the TPU engines: the DGraph pins
(`/root/reference/src/checker.rs:350-415`) — including the documented
unsoundness for cycles/DAG-rejoins (`bfs.rs:239-256`) — must hold
identically on both device modes."""

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.core import Property  # noqa: E402
from stateright_tpu.models.fixtures import PackedDGraph  # noqa: E402


def eventually_odd():
    return Property.eventually("odd", lambda _, s: s % 2 == 1)


def check_tpu(graph, mode):
    return (graph.checker()
            .tpu_options(capacity=1 << 10, mode=mode, fmax=16)
            .spawn_tpu().join())


MODES = ["device", "level"]


@pytest.mark.parametrize("mode", MODES)
class TestTpuEventually:
    def test_can_validate(self, mode):
        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([1])
             .with_path([2, 3])
             .with_path([2, 6, 7])
             .with_path([4, 9, 10]))
        check_tpu(g, mode).assert_properties()
        check_tpu(PackedDGraph.with_property(eventually_odd())
                  .with_path([2, 6, 7]), mode).assert_properties()

    def test_can_discover_counterexample(self, mode):
        c = check_tpu(PackedDGraph.with_property(eventually_odd())
                      .with_path([0, 1]).with_path([0, 2]), mode)
        assert c.discovery("odd").into_states() == [0, 2]

        c = check_tpu(PackedDGraph.with_property(eventually_odd())
                      .with_path([0, 1]).with_path([2, 4]), mode)
        assert c.discovery("odd").into_states() == [2, 4]

        c = check_tpu(PackedDGraph.with_property(eventually_odd())
                      .with_path([0, 1, 4, 6]).with_path([2, 4, 8]), mode)
        # two even terminals (6 via 4, 8 via 4); the engine reports one —
        # both witnesses are valid (the reference's multithreaded engines
        # are similarly nondeterministic)
        states = c.discovery("odd").into_states()
        assert states in ([2, 4, 6], [2, 4, 8], [0, 1, 4, 6], [0, 1, 4, 8])

    def test_fixme_can_miss_counterexample_when_revisiting_a_state(
            self, mode):
        # cycles / DAG rejoins are not treated as terminal — replicate the
        # reference's accepted unsoundness exactly (checker.rs:402-414)
        c = check_tpu(PackedDGraph.with_property(eventually_odd())
                      .with_path([0, 2, 4, 2]), mode)
        assert c.discovery("odd") is None
        c = check_tpu(PackedDGraph.with_property(eventually_odd())
                      .with_path([0, 2, 4]).with_path([1, 4, 6]), mode)
        assert c.discovery("odd") is None

    def test_differential_with_host(self, mode):
        # same graph family: device reached set == host reached set
        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 1, 4, 6]).with_path([2, 4, 8]))
        host = g.check()
        dev = check_tpu(g, mode)
        assert (dev.generated_fingerprints()
                == host.generated_fingerprints())


class _HostEvDGraph(PackedDGraph):
    """PackedDGraph whose eventually-property is HOST-evaluated: the
    packed placeholder bit is always False, so the device cannot clear
    it — only the engine's per-level host correction can."""

    host_property_indices = (0,)

    @staticmethod
    def from_graph(g: PackedDGraph) -> "_HostEvDGraph":
        h = _HostEvDGraph(g.prop)
        h.inits = set(g.inits)
        h.edges = {k: set(v) for k, v in g.edges.items()}
        return h

    def packed_properties(self, words):
        import jax.numpy as jnp
        return jnp.zeros((1,), bool)

    def host_property_key(self, row) -> bytes:
        import numpy as np
        return np.asarray(row, np.uint32).tobytes()

    def cache_key(self):
        return ("hostev",) + super().cache_key()


class TestHostEventuallyOnDevice:
    """Host-evaluated EVENTUALLY properties on the device engine: the
    host corrects each new state's ebits before enqueue, so terminal
    flushes match the host engines' verdicts exactly."""

    def _make(self, paths):
        g = PackedDGraph.with_property(eventually_odd())
        for p in paths:
            g = g.with_path(p)
        return _HostEvDGraph.from_graph(g)

    def _check(self, g):
        return (g.checker().tpu_options(capacity=1 << 10, fmax=16)
                .spawn_tpu().join())

    def test_counterexample_found(self):
        # 0 -> 2 -> 4, all even: the terminal flush must fire from the
        # host-corrected (never-cleared) bit
        c = self._check(self._make([[0, 2, 4]]))
        states = c.assert_any_discovery("odd").into_states()
        assert states == [0, 2, 4]

    def test_satisfied_path_clears(self):
        # 0 -> 1(odd) -> 2: the host clears the bit at 1, so the
        # terminal 2 must NOT flush a counterexample
        self._check(self._make([[0, 1, 2]])).assert_properties()

    def test_matches_host_bfs(self):
        for paths in ([[1], [2, 3], [2, 6, 7], [4, 9, 10]],
                      [[0, 2, 4], [1, 4, 6]],   # DAG-rejoin caveat
                      [[0, 2, 4, 2]]):          # cycle caveat
            g = self._make(paths)
            dev = self._check(g)
            host = g.checker().spawn_bfs().join()
            assert (dev.discovery("odd") is None) \
                == (host.discovery("odd") is None), paths
            assert (dev.generated_fingerprints()
                    == host.generated_fingerprints())

    def test_device_mode_rejected(self):
        g = self._make([[0, 2]])
        with pytest.raises(NotImplementedError):
            (g.checker().tpu_options(capacity=1 << 10, mode="device")
             .spawn_tpu().join())
