"""spawn_tpu host-vs-device race (checker/race.py): tiny models must
answer at host speed; device-only features must bypass the race; a
device failure must not beat a correct host result."""

import time

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.examples.increment_lock import IncrementLock  # noqa: E402
from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402


class TestRace:
    def test_small_model_fast_and_exact(self):
        jax.devices()  # engine warm-up out of the timed region
        t0 = time.perf_counter()
        ck = (IncrementLock(3).checker().tpu_options(capacity=1 << 14)
              .spawn_tpu().join())
        dt = time.perf_counter() - t0
        assert ck.unique_state_count() == 61
        assert dt < 0.3, dt  # BASELINE.json time-to-counterexample bar
        ck.assert_properties()

    def test_full_enumeration_agnostic_to_winner(self):
        # either engine winning must produce the exact enumeration
        ck = (TwoPhaseSys(3).checker().tpu_options(capacity=1 << 12)
              .spawn_tpu().join())
        assert ck.unique_state_count() == 288  # 2pc.rs:128
        host = TwoPhaseSys(3).checker().spawn_bfs().join()
        assert ck.generated_fingerprints() == host.generated_fingerprints()

    def test_device_failure_defers_to_host(self):
        # the device run hits a packed-encoding overflow (fatal on the
        # device path) while the host model is fine; the budgeted host
        # racer completes, so the raced run returns the correct result
        # instead of raising (race=False pins the raise — see
        # test_tpu_engine.TestModelOverflowFatal)
        from test_tpu_engine import _OverflowingEquation

        class _TinyOverflow(_OverflowingEquation):
            # bound the host search so it finishes well inside the race
            # budget; the device still overflows at x > 5 first
            def within_boundary(self, state):
                return state[0] <= 20 and state[1] <= 20

        model = _TinyOverflow(2, 0, 10**9)  # unsatisfiable: full walk
        ck = (model.checker().tpu_options(capacity=1 << 14)
              .spawn_tpu().join())
        assert ck.unique_state_count() > 0
        host = _TinyOverflow(2, 0, 10**9).checker().spawn_bfs().join()
        assert ck.unique_state_count() == host.unique_state_count()

    def test_race_ineligible_paths(self):
        from stateright_tpu.checker.race import race_eligible
        b = TwoPhaseSys(3).checker()
        assert race_eligible(b)
        assert not race_eligible(TwoPhaseSys(3).checker()
                                 .tpu_options(race=False))
        assert not race_eligible(TwoPhaseSys(3).checker()
                                 .tpu_options(mode="device"))
        assert not race_eligible(TwoPhaseSys(3).checker()
                                 .tpu_options(resumable=True))
        m = TwoPhaseSys(3)
        assert not race_eligible(m.checker().symmetry_fn(m.representative))

    def test_report_streams_progress(self):
        import io
        out = io.StringIO()
        ck = (TwoPhaseSys(3).checker().tpu_options(capacity=1 << 12)
              .spawn_tpu().report(out))
        text = out.getvalue()
        assert "Done. states=" in text
        assert ck.unique_state_count() == 288


def test_race_budget_option():
    # tpu_options(race_budget=...) overrides the 1.5 s host-racer budget
    import pytest
    pytest.importorskip("jax")
    from stateright_tpu.checker.race import RacingChecker
    from stateright_tpu.models.packed import PackedLinearEquation

    ck = (PackedLinearEquation(2, 4, 8).checker()
          .tpu_options(race_budget=9.0, capacity=1 << 10).spawn_tpu())
    assert isinstance(ck, RacingChecker)
    assert ck.HOST_BUDGET_S == 9.0
    assert RacingChecker.HOST_BUDGET_S == 1.5  # class default untouched
    ck.join().assert_any_discovery("solvable")
