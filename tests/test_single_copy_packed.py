"""Packed single-copy register: the device engine catching a
linearizability violation (`/root/reference/examples/single-copy-register.rs:84-122`)."""

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.examples.single_copy_packed import PackedSingleCopy  # noqa: E402
from stateright_tpu.models.packed import validate_packed_model  # noqa: E402


class TestPackedSingleCopy:
    def test_contract_full(self):
        # all 93 reachable states of the 1-server config
        assert validate_packed_model(
            PackedSingleCopy(2, server_count=1), max_states=200) == 93

    def test_one_server_linearizable_93(self):
        ck = (PackedSingleCopy(2, server_count=1).checker()
              .tpu_options(capacity=1 << 10).spawn_tpu().join())
        assert ck.unique_state_count() == 93
        ck.assert_properties()

    def test_two_servers_counterexample(self):
        # the headline: two unreplicated servers are NOT linearizable and
        # the device engine must produce a counterexample whose final
        # history really fails the linearizability search
        ck = (PackedSingleCopy(2, server_count=2).checker()
              .tpu_options(capacity=1 << 12).spawn_tpu().join())
        path = ck.assert_any_discovery("linearizable")
        last = path.last_state()
        assert last.history.serialized_history() is None

    def test_two_servers_host_agrees(self):
        host = (PackedSingleCopy(2, server_count=2).checker()
                .spawn_bfs().join())
        assert host.discovery("linearizable") is not None
