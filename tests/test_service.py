"""Checking-as-a-service (stateright_tpu/service + tools/jobs.py).

The load-bearing guarantees, all pinned on the CPU-forced virtual mesh:

* **concurrency parity** — two jobs running concurrently on DISJOINT
  power-of-two device subsets each produce results bit-identical to a
  solo run at the same mesh width (fingerprint-set digests match);
* **pause/resume parity** — a paused job's checkpoint resumes (in this
  process or after a service restart) to the identical reached set;
* **preemption parity** — a D=4 job paused by the scheduler and
  resumed on a D=2 subset equals an uninterrupted D=2 run (the
  degradation ladder's guarantee, now scheduler-driven);
* **restart survival** — a service killed (SIGKILL) mid-run resumes
  the RUNNING job from its last autosave on the next boot and finishes
  with the identical fingerprint set (subprocess test);
* ``bench.py --service-smoke`` lands a crash-proof ``"service": true``
  contract line, rc=0, CPU only.
"""

import hashlib
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402
from stateright_tpu.service import (DONE, PAUSED, RUNNING,  # noqa: E402
                                    DevicePool, JobSpec, JobStore,
                                    Scheduler, StepDriver, serve_jobs)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: pinned engine shapes (shared with tests/test_resilience.py so the
#: persistent compile cache is reused): small, multi-chunk runs
OPTS = {"capacity": 1 << 12, "fmax": 64, "chunk_steps": 2}


def _digest(checker) -> str:
    fps = sorted(int(f) for f in checker.generated_fingerprints())
    return hashlib.sha256("\n".join(map(str, fps)).encode()).hexdigest()


def _solo(n: int, **extra):
    return (TwoPhaseSys(n).checker()
            .tpu_options(race=False, **OPTS, **extra)
            .spawn_tpu().join())


@pytest.fixture(scope="module")
def solo_2pc3():
    return _solo(3)


@pytest.fixture(scope="module")
def solo_2pc4():
    return _solo(4)


def _mesh(n):
    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"need {n} devices, have {len(devices)}")
    from jax.sharding import Mesh
    return Mesh(np.array(devices[:n]), ("shards",))


# --- DevicePool: the ladder's subset carving as capacity allocation ----

class TestDevicePool:
    def test_carve_disjoint_and_merge(self):
        pool = DevicePool(list(range(8)))
        l4 = pool.acquire(4)
        l2a = pool.acquire(2)
        l2b = pool.acquire(2)
        assert l4.width == 4 and l2a.width == l2b.width == 2
        # power-of-two aligned, pairwise disjoint
        spans = [(l.offset, l.offset + l.width) for l in (l4, l2a, l2b)]
        for lease in (l4, l2a, l2b):
            assert lease.offset % lease.width == 0
        for i, (a0, a1) in enumerate(spans):
            for b0, b1 in spans[i + 1:]:
                assert a1 <= b0 or b1 <= a0
        assert pool.acquire(1) is None  # fully carved
        pool.release(l2a)
        assert pool.acquire(2).offset == l2a.offset
        # release everything: buddies merge back to the full mesh
        pool2 = DevicePool(list(range(8)))
        leases = [pool2.acquire(2) for _ in range(4)]
        assert all(leases)
        for lease in leases:
            pool2.release(lease)
        assert pool2.largest_free() == 8

    def test_pow2_floor_and_rejects(self):
        pool = DevicePool(list(range(5)))  # floor -> 4
        assert pool.width == 4
        assert pool.acquire(8) is None
        assert pool.acquire(3) is None  # not a power of two
        lease = pool.acquire(4)
        assert lease.devices == (0, 1, 2, 3)
        pool.release(lease)
        assert pool.free_width() == 4


# --- two-level pool: slices within hosts, hosts within the fleet ------

class TestTwoLevelPool:
    def test_two_hosts_carve_and_merge(self):
        pool = DevicePool(list(range(8)), hosts=[0] * 4 + [1] * 4)
        assert pool.host_count == 2 and pool.host_width == 4
        assert pool.width == 8
        # fleet-wide lease: both hosts, whole
        l8 = pool.acquire(8)
        assert l8.width == 8 and l8.hosts == (0, 1)
        assert pool.acquire(1) is None
        pool.release(l8)
        assert pool.largest_free() == 8
        # slice leases never straddle hosts; best-fit packs the
        # partially-carved host first, preserving whole hosts
        l2 = pool.acquire(2)
        assert l2.hosts == (0,)
        l4 = pool.acquire(4)
        assert l4.hosts == (1,)  # host 0 is carved; host 1 goes whole
        l2b = pool.acquire(2)
        assert l2b.hosts == (0,)  # packs into host 0's remainder
        assert pool.acquire(2) is None
        for lease in (l2, l4, l2b):
            pool.release(lease)
        assert pool.largest_free() == 8  # both levels merged back

    def test_wide_leases_take_whole_free_hosts_only(self):
        pool = DevicePool(list(range(4)), hosts=[0, 0, 1, 1])
        lone = pool.acquire(1)
        assert lone.hosts == (0,)
        # width == host_width needs a FULLY-FREE host, not host 0's
        # fragmented remainder
        l2 = pool.acquire(2)
        assert l2.hosts == (1,)
        assert l2.offset % l2.width == 0
        assert pool.acquire(2) is None  # host 0 has 1 free, fragmented
        assert pool.acquire(4) is None  # no fleet-wide block either
        pool.release(lone)
        pool.release(l2)
        assert pool.acquire(4).hosts == (0, 1)

    def test_unequal_hosts_trim_to_common_pow2(self):
        # 3+3 devices: per-host floor 2, fleet width 4 — and a slice
        # lease can never span the host boundary (the old flat floor
        # of 6 -> 4 would have straddled it)
        pool = DevicePool(list(range(6)), hosts=[0, 0, 0, 1, 1, 1])
        assert pool.host_width == 2 and pool.width == 4
        la = pool.acquire(2)
        lb = pool.acquire(2)
        spans = sorted([la.devices, lb.devices])
        assert spans == [(0, 1), (3, 4)]

    def test_plain_device_list_is_one_anonymous_host(self):
        # hosts=None on non-jax objects: process_index defaults to 0,
        # preserving the original single-level behavior
        pool = DevicePool(list(range(8)))
        assert pool.host_count == 1 and pool.host_width == 8
        assert pool.acquire(8).hosts == (0,)

    def test_per_host_free_accounting(self):
        pool = DevicePool(list(range(8)), hosts=["a"] * 4 + ["b"] * 4)
        assert pool.per_host_free() == {"a": 4, "b": 4}
        lease = pool.acquire(2)
        assert pool.per_host_free() == {"a": 2, "b": 4}
        pool.release(lease)
        assert pool.per_host_free() == {"a": 4, "b": 4}


class TestElasticPool:
    """Rolling host join/retire on the two-level pool: joined width
    buddy-merges into the fleet level, retired FREE width is withdrawn
    immediately, and busy slices on a retiring host drain without
    ever re-entering the free lists."""

    def test_add_host_doubles_fleet_width(self):
        pool = DevicePool(list(range(4)), hosts=["h0"] * 4)
        assert pool.acquire(8) is None
        assert pool.add_host("h1", [4, 5, 6, 7]) == 1
        assert pool.width == 8 and pool.active_host_count == 2
        l8 = pool.acquire(8)
        assert l8 is not None and l8.hosts == ("h0", "h1")
        assert l8.devices == (0, 1, 2, 3, 4, 5, 6, 7)
        pool.release(l8)
        assert pool.largest_free() == 8

    def test_add_host_rejects_duplicates_and_narrow_hosts(self):
        pool = DevicePool(list(range(4)), hosts=["h0"] * 4)
        with pytest.raises(ValueError, match="already"):
            pool.add_host("h0", [9, 10, 11, 12])
        with pytest.raises(ValueError, match="host_width"):
            pool.add_host("h1", [9])

    def test_retire_withdraws_free_width_immediately(self):
        pool = DevicePool(list(range(8)), hosts=["h0"] * 4 + ["h1"] * 4)
        assert pool.retire_host("h1") == [4, 5, 6, 7]
        assert pool.width == 4 and pool.active_host_count == 1
        assert pool.per_host_free() == {"h0": 4}
        assert pool.acquire(4).hosts == ("h0",)
        assert pool.acquire(1) is None
        with pytest.raises(ValueError, match="already retired"):
            pool.retire_host("h1")

    def test_retire_drains_busy_leases_without_refreeing_them(self):
        # the 8-device carve: h1's half is BUSY at retire time — its
        # eventual release is discarded, while h0's merges back whole
        pool = DevicePool(list(range(8)), hosts=["h0"] * 4 + ["h1"] * 4)
        on_h0 = pool.acquire(4)
        on_h1 = pool.acquire(4)
        assert on_h0.hosts == ("h0",) and on_h1.hosts == ("h1",)
        pool.retire_host("h1")
        assert pool.free_width() == 0
        pool.release(on_h1)  # drained, NOT re-freed
        assert pool.free_width() == 0
        pool.release(on_h0)
        assert pool.free_width() == 4
        assert pool.acquire(4).hosts == ("h0",)

    def test_retire_breaks_the_spanning_block_keeping_survivors(self):
        pool = DevicePool(list(range(8)), hosts=["h0"] * 4 + ["h1"] * 4)
        assert pool.largest_free() == 8  # one merged fleet-level block
        pool.retire_host("h0")
        assert pool.width == 4
        lease = pool.acquire(4)
        assert lease is not None and lease.hosts == ("h1",)
        assert pool.per_host_free() == {"h1": 0}


class TestTwoHostScheduler:
    def test_grants_jobs_across_two_simulated_hosts(self, tmp_path,
                                                    solo_2pc3):
        # ACCEPTANCE: one scheduler packs jobs across the whole fleet —
        # four width-1 jobs over a 2-host × 2-device pool land on BOTH
        # hosts (recorded per job), every result bit-identical to the
        # solo oracle, and the buddies merge back on completion
        if len(jax.devices()) < 4:
            pytest.skip("need 4 devices")
        sched = Scheduler(JobStore(tmp_path),
                          devices=jax.devices()[:4],
                          hosts=["h0", "h0", "h1", "h1"])
        jobs = [sched.submit(JobSpec("twopc", args=[3], options=OPTS))
                for _ in range(4)]
        by_host = {}
        for job in jobs:
            assert sched.wait(job.id, timeout=180.0) == "done"
            result = job.read_result()
            assert result["fingerprints_sha256"] == _digest(solo_2pc3)
            for h in job.status["hosts"]:
                by_host[h] = by_host.get(h, 0) + 1
        assert by_host == {"h0": 2, "h1": 2}
        prof = sched.profile()
        assert prof["jobs_done"] == 4
        assert prof["hosts"] == 2
        # completion merged the carves back through both levels. The
        # state flip deliberately precedes the worker's lease release
        # (wait() unblocks on the status artifact), so give the last
        # finally block a moment to land its release
        deadline = time.monotonic() + 5.0
        while sched._pool.largest_free() != 4 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sched._pool.largest_free() == 4
        assert sched._pool.per_host_free() == {"h0": 2, "h1": 2}
        sched.shutdown()


# --- elastic flex: promote-on-freed-width, demote-under-pressure ------

class TestFlexController:
    """SLO-driven flex (``Scheduler(flex=True)``): width freed by a
    finishing job promotes the hungriest RUNNING job in place (the
    release path re-checks running jobs, not only the queue); queue
    pressure demotes the over-width job first; a rolling host join
    widens a running job without a restart. Digests stay pinned to the
    solo oracles through every width change."""

    def test_release_promotes_running_job_in_place(self, tmp_path,
                                                   solo_2pc3,
                                                   solo_2pc4):
        # the 8-device carve: B holds half the pool, so A (wants 8)
        # lands on 4; when B finishes and its buddies merge free, the
        # flex pass doubles A mid-run — promotes == 1, digest pinned
        if len(jax.devices()) < 8:
            pytest.skip("need 8 devices")
        sched = Scheduler(JobStore(tmp_path), devices=jax.devices(),
                          hosts=["h0"] * 8, flex=True,
                          flex_interval=0.0, step_budget=1)
        try:
            b = sched.submit(JobSpec("twopc", args=[3], options=OPTS,
                                     width=4))
            a = sched.submit(JobSpec("twopc", args=[4], options=OPTS,
                                     width=8, step_delay=0.02))
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and not \
                    sched.job(a.id).status.get("granted_width"):
                time.sleep(0.05)
            assert sched.job(a.id).status["granted_width"] == 4
            assert sched.wait(b.id, timeout=180.0) == "done"
            assert sched.wait(a.id, timeout=240.0) == "done"
            assert sched.job(a.id).status["granted_width"] == 8
            prof = sched.profile()
            assert prof.get("promotes") == 1
            assert prof.get("demotes", 0) == 0
            # the promote lease releases in the worker's exit path,
            # just AFTER the state flip wait() unblocks on — settle
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline \
                    and sched.profile().get("flex_width"):
                time.sleep(0.05)
            assert sched.profile().get("flex_width") == 0
            assert sched.job(b.id).read_result()[
                "fingerprints_sha256"] == _digest(solo_2pc3)
            assert sched.job(a.id).read_result()[
                "fingerprints_sha256"] == _digest(solo_2pc4)
        finally:
            sched.shutdown()

    def test_queue_pressure_demotes_the_overwidth_job(self, tmp_path,
                                                      solo_2pc3,
                                                      solo_2pc4):
        # C runs wide and alone; a higher-priority arrival finds the
        # pool fully carved — flex picks the width>1 job to DEMOTE
        # (checkpoint, release, requeue narrower) rather than a blind
        # preempt; both resume/finish with pinned digests
        if len(jax.devices()) < 4:
            pytest.skip("need 4 devices")
        sched = Scheduler(JobStore(tmp_path),
                          devices=jax.devices()[:4], hosts=["h0"] * 4,
                          flex=True, flex_interval=0.0, step_budget=1)
        try:
            c = sched.submit(JobSpec("twopc", args=[4], options=OPTS,
                                     width=4, step_delay=0.05))
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline \
                    and not sched.job(c.id).status.get("first_chunk_at"):
                time.sleep(0.05)
            d = sched.submit(JobSpec("twopc", args=[3], options=OPTS,
                                     width=4, priority=5))
            assert sched.wait(d.id, timeout=180.0) == "done"
            assert sched.wait(c.id, timeout=240.0) == "done"
            prof = sched.profile()
            assert prof.get("demotes") == 1
            assert prof.get("preemptions") == 1
            assert sched.job(c.id).status.get("resume") is True
            assert sched.job(d.id).read_result()[
                "fingerprints_sha256"] == _digest(solo_2pc3)
            assert sched.job(c.id).read_result()[
                "fingerprints_sha256"] == _digest(solo_2pc4)
            demotes = [json.loads(ln) for ln in open(
                sched.store.service_trace_path)
                if '"job_demote"' in ln]
            assert demotes and demotes[0]["job"] == c.id
            assert demotes[0]["width"] == 4
        finally:
            sched.shutdown()

    def test_host_join_widens_a_running_job(self, tmp_path, solo_2pc4):
        # rolling join: the fleet starts one host wide; h1 joins
        # mid-run and the under-granted job is promoted onto it
        if len(jax.devices()) < 8:
            pytest.skip("need 8 devices")
        devs = jax.devices()
        sched = Scheduler(JobStore(tmp_path), devices=devs[:4],
                          hosts=["h0"] * 4, flex=True,
                          flex_interval=0.0, step_budget=1)
        try:
            a = sched.submit(JobSpec("twopc", args=[4], options=OPTS,
                                     width=8, step_delay=0.02))
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and not \
                    sched.job(a.id).status.get("granted_width"):
                time.sleep(0.05)
            assert sched.job(a.id).status["granted_width"] == 4
            assert sched.join_host("h1", devs[4:8]) == 1
            assert sched.pool_width() == 8
            assert sched.wait(a.id, timeout=240.0) == "done"
            assert sched.job(a.id).status["granted_width"] == 8
            assert sorted(sched.job(a.id).status["hosts"]) \
                == ["h0", "h1"]
            prof = sched.profile()
            assert prof.get("promotes") == 1
            assert prof.get("hosts") == 2
            assert sched.job(a.id).read_result()[
                "fingerprints_sha256"] == _digest(solo_2pc4)
            # the job is done: h1 retires with nothing to drain
            assert len(sched.leave_host("h1")) == 4
            assert sched.pool_width() == 4
            assert sched.profile().get("hosts") == 1
        finally:
            sched.shutdown()


# --- StepDriver: start -> step(budget) -> ... -> finish ---------------

class TestStepDriver:
    def test_stepped_run_matches_blocking(self, solo_2pc3):
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, **OPTS).spawn_tpu())
        driver = StepDriver(ck).start()
        with pytest.raises(RuntimeError, match="start"):
            driver.start()
        while driver.step(2) == RUNNING:
            pass
        assert driver.status == DONE
        assert ck.is_done()
        assert _digest(ck) == _digest(solo_2pc3)
        assert ck.unique_state_count() == 288
        # a claimed run cannot also start its background thread, but
        # join()/report() after the driver finished still work
        assert ck.join() is ck

    def test_pause_checkpoint_resumes_bit_identical(self, tmp_path,
                                                    solo_2pc3):
        path = tmp_path / "pause.npz"
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, **{**OPTS, "chunk_steps": 1})
              .spawn_tpu())
        driver = StepDriver(ck).start()
        assert driver.step(1) == RUNNING  # genuinely mid-run
        ckpt = driver.pause(os.fspath(path))
        assert driver.status == PAUSED and ck.paused()
        assert ckpt == os.fspath(path) and path.exists()
        assert ck.profile()["pauses"] == 1
        assert 0 < ck.unique_state_count() < 288
        resumed = (TwoPhaseSys(3).checker()
                   .tpu_options(race=False, **OPTS)
                   .resume_from(path).spawn_tpu().join())
        assert resumed.unique_state_count() == 288
        assert _digest(resumed) == _digest(solo_2pc3)

    def test_pause_after_finish_reports_done(self, tmp_path):
        ck = (TwoPhaseSys(2).checker()
              .tpu_options(race=False, **OPTS).spawn_tpu())
        driver = StepDriver(ck).start()
        driver.drain()
        assert driver.status == DONE
        assert driver.pause(os.fspath(tmp_path / "p.npz")) is None
        assert driver.status == DONE and not ck.paused()

    def test_pause_needs_a_destination(self):
        ck = (TwoPhaseSys(2).checker()
              .tpu_options(race=False, **OPTS).spawn_tpu())
        with pytest.raises(ValueError, match="artifact_dir"):
            ck.request_pause()


# --- job-scoped artifacts ---------------------------------------------

class TestArtifactDir:
    def test_expands_and_isolates(self, tmp_path):
        a_dir, b_dir = tmp_path / "a", tmp_path / "b"
        runs = []
        for d in (a_dir, b_dir):
            runs.append(
                TwoPhaseSys(2).checker()
                .tpu_options(race=False, **OPTS,
                             artifact_dir=os.fspath(d),
                             autosave_interval=1)
                .spawn_tpu().join())
        for d in (a_dir, b_dir):
            assert (d / "trace.jsonl").exists()
            assert (d / "autosave.npz").exists()
        # the two runs' artifacts are fully separate files
        assert (a_dir / "trace.jsonl").read_text() \
            != "" != (b_dir / "trace.jsonl").read_text()
        prof = runs[0].profile()
        assert prof.get("autosaves", 0) >= 1

    def test_explicit_knob_wins(self, tmp_path):
        explicit = tmp_path / "elsewhere.jsonl"
        ck = (TwoPhaseSys(2).checker()
              .tpu_options(race=False, **OPTS,
                           artifact_dir=os.fspath(tmp_path / "job"),
                           trace=os.fspath(explicit))
              .spawn_tpu().join())
        assert ck.is_done()
        assert explicit.exists()
        assert not (tmp_path / "job" / "trace.jsonl").exists()


# --- the scheduler -----------------------------------------------------

class TestScheduler:
    def test_concurrent_jobs_disjoint_subsets_bit_identical(
            self, tmp_path, solo_2pc3, solo_2pc4):
        # ACCEPTANCE: two jobs submitted concurrently to a 2-device
        # (CPU-forced) pool run on disjoint width-1 subsets and each
        # returns results bit-identical to a solo run
        if len(jax.devices()) < 2:
            pytest.skip("need 2 devices")
        sched = Scheduler(JobStore(tmp_path), devices=jax.devices()[:2])
        j1 = sched.submit(JobSpec("twopc", args=[3], options=OPTS,
                                  step_delay=0.25))
        j2 = sched.submit(JobSpec("twopc", args=[4], options=OPTS,
                                  step_delay=0.25))
        assert sched.wait(j1.id, timeout=120.0) == "done"
        assert sched.wait(j2.id, timeout=120.0) == "done"
        r1, r2 = j1.read_result(), j2.read_result()
        assert r1["unique_state_count"] == 288
        assert r2["unique_state_count"] == solo_2pc4.unique_state_count()
        assert r1["fingerprints_sha256"] == _digest(solo_2pc3)
        assert r2["fingerprints_sha256"] == _digest(solo_2pc4)
        # they really ran side by side on their own devices
        assert j1.status["granted_width"] == 1
        assert j2.status["granted_width"] == 1
        assert j1.status["running_at"] < j2.status["done_at"]
        assert j2.status["running_at"] < j1.status["done_at"]
        prof = sched.profile()
        assert prof["jobs_submitted"] == 2 and prof["jobs_done"] == 2
        sched.shutdown()

    def test_pause_restart_resume_parity(self, tmp_path, solo_2pc4):
        # pause -> (new scheduler on the same store = a service
        # restart) -> resume: the finished job equals the solo run
        sched = Scheduler(JobStore(tmp_path),
                          devices=jax.devices()[:1])
        job = sched.submit(JobSpec("twopc", args=[4],
                                   options={**OPTS, "chunk_steps": 1,
                                            "autosave_interval": 1},
                                   step_delay=0.2))
        assert sched.wait(job.id, timeout=60.0,
                          states=("running",)) == "running"
        assert sched.pause(job.id)
        assert sched.wait(job.id, timeout=60.0,
                          states=("paused",)) == "paused"
        assert job.has_checkpoint()
        sched.shutdown()

        sched2 = Scheduler(JobStore(tmp_path),
                           devices=jax.devices()[:1])
        job2 = sched2.job(job.id)
        assert job2.state == "paused"  # paused jobs wait for resume
        assert sched2.resume(job.id)
        assert sched2.wait(job.id, timeout=120.0) == "done"
        result = sched2.job(job.id).read_result()
        assert result["unique_state_count"] == \
            solo_2pc4.unique_state_count()
        assert result["fingerprints_sha256"] == _digest(solo_2pc4)
        sched2.shutdown()

    @pytest.mark.slow
    def test_preempt_d4_resumes_at_d2_equals_uninterrupted_d2(
            self, tmp_path):
        # ACCEPTANCE: preemption = pause the lowest-priority job,
        # resume on a smaller subset — a D=4 job paused mid-run and
        # resumed at D=2 equals an uninterrupted D=2 run (the ladder's
        # parity guarantee, now scheduler-driven)
        # (-m slow since round 11: the slowest service pin after the
        # sigkill subprocess; cross-width pause/resume parity stays in
        # tier-1 via test_pause_restart_resume_parity, and the batch
        # storm pin needed the budget headroom)
        if len(jax.devices()) < 4:
            pytest.skip("need 4 devices")
        clean_d2 = (TwoPhaseSys(3).checker()
                    .tpu_options(race=False, **OPTS, mesh=_mesh(2))
                    .spawn_tpu().join())
        sched = Scheduler(JobStore(tmp_path), devices=jax.devices()[:4])
        lo = sched.submit(JobSpec("twopc", args=[3],
                                  options={**OPTS, "chunk_steps": 1},
                                  width=4, priority=0, step_delay=0.25))
        assert sched.wait(lo.id, timeout=60.0,
                          states=("running",)) == "running"
        hi = sched.submit(JobSpec("twopc", args=[2], options=OPTS,
                                  width=2, priority=5))
        assert sched.wait(hi.id, timeout=120.0) == "done"
        assert sched.wait(lo.id, timeout=180.0) == "done"
        prof = sched.profile()
        assert prof.get("preemptions", 0) >= 1
        assert lo.status.get("preempted") is True
        assert lo.status["granted_width"] == 2  # resumed SMALLER
        result = lo.read_result()
        assert result["unique_state_count"] == \
            clean_d2.unique_state_count() == 288
        assert result["fingerprints_sha256"] == _digest(clean_d2)
        assert set(p["name"] for p in result["properties"]) == \
            set(p.name for p in clean_d2.model().properties())
        sched.shutdown()

    def test_cancel_running_job(self, tmp_path):
        sched = Scheduler(JobStore(tmp_path),
                          devices=jax.devices()[:1])
        job = sched.submit(JobSpec("twopc", args=[4],
                                   options={**OPTS, "chunk_steps": 1},
                                   step_delay=0.25))
        sched.wait(job.id, timeout=60.0, states=("running",))
        assert sched.cancel(job.id)
        assert sched.wait(job.id, timeout=60.0) == "cancelled"
        sched.shutdown()

    def test_unknown_model_fails_loudly(self, tmp_path):
        sched = Scheduler(JobStore(tmp_path),
                          devices=jax.devices()[:1])
        job = sched.submit(JobSpec("no-such-model", args=[]))
        assert sched.wait(job.id, timeout=60.0) == "failed"
        assert "unknown model" in job.status["error"]
        assert sched.profile()["jobs_failed"] == 1
        sched.shutdown()


# --- fleet quarantine (silent-corruption defense) ----------------------

class TestQuarantine:
    """A device the chunk auditor caught lying is withheld from every
    future grant (persisted in the service root, so it survives
    restarts, and surfaced in ``/utilization``); re-admission only
    through :meth:`Scheduler.audit_probe`."""

    def test_lying_device_quarantined_persisted_probed(self, tmp_path,
                                                       solo_2pc3):
        if len(jax.devices()) < 2:
            pytest.skip("need 2 devices")
        sched = Scheduler(JobStore(tmp_path), devices=jax.devices()[:2])
        job = sched.submit(JobSpec(
            "twopc", args=[3],
            options={**OPTS, "audit": 1, "retries": 2, "backoff": 0.0,
                     "corrupt_hook": lambda o, d: 0 if o == 2 else None}))
        assert sched.wait(job.id, timeout=120.0) == "done", job.state
        result = job.read_result()
        # the lying chip did not poison the artifact: digest parity
        # with a solo run, bound into the integrity chain
        assert result["fingerprints_sha256"] == _digest(solo_2pc3)
        assert result["chain_head"] and result["integrity"]
        quarantined = sched.quarantined()
        assert len(quarantined) == 1
        assert sched.utilization()["quarantined"] == quarantined
        assert os.path.exists(os.path.join(str(tmp_path),
                                           "quarantine.json"))
        # the pool never grants the blamed chip again: a clean job
        # still completes on the surviving device
        j2 = sched.submit(JobSpec("twopc", args=[3], options=OPTS))
        assert sched.wait(j2.id, timeout=120.0) == "done"
        assert sched.quarantined() == quarantined
        sched.shutdown()

        # restart survival: the blame record reloads from the service
        # root and the chip is carved out of the fresh pool
        sched2 = Scheduler(JobStore(tmp_path),
                           devices=jax.devices()[:2])
        j3 = sched2.submit(JobSpec("twopc", args=[3], options=OPTS))
        assert sched2.wait(j3.id, timeout=120.0) == "done"
        assert sched2.quarantined() == quarantined

        # probation: a FAILING audit probe keeps it out, a passing one
        # buddy-merges the width-1 block back and drops the record
        assert sched2.audit_probe(
            quarantined[0], oracle=lambda rows, dev: [1]) is False
        assert sched2.quarantined() == quarantined
        assert sched2.audit_probe(quarantined[0]) is True
        assert sched2.quarantined() == []
        with open(os.path.join(str(tmp_path), "quarantine.json")) as f:
            assert json.load(f) == {}
        # the freed device really is grantable: two jobs run
        # concurrently on the 2-device pool again
        a = sched2.submit(JobSpec("twopc", args=[3], options=OPTS,
                                  step_delay=0.25))
        b = sched2.submit(JobSpec("twopc", args=[3], options=OPTS,
                                  step_delay=0.25))
        assert sched2.wait(a.id, timeout=120.0) == "done"
        assert sched2.wait(b.id, timeout=120.0) == "done"
        assert a.status["running_at"] < b.status["done_at"]
        assert b.status["running_at"] < a.status["done_at"]
        sched2.shutdown()

    def test_probe_unknown_device_raises(self, tmp_path):
        sched = Scheduler(JobStore(tmp_path),
                          devices=jax.devices()[:1])
        with pytest.raises(ValueError, match="not quarantined"):
            sched.audit_probe("999")
        sched.shutdown()


# --- HTTP API + CLI artifacts ------------------------------------------

class TestServiceApi:
    def test_http_end_to_end(self, tmp_path):
        from stateright_tpu.obs import validate_event

        sched = Scheduler(JobStore(tmp_path),
                          devices=jax.devices()[:1])
        handle = serve_jobs(sched, ("127.0.0.1", 0))
        base = handle.url
        try:
            body = json.dumps({"model": "twopc", "args": [3],
                               "options": OPTS}).encode()
            req = urllib.request.Request(
                f"{base}/jobs", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as resp:
                job_id = json.loads(resp.read())["id"]

            deadline = time.monotonic() + 120
            state = None
            while time.monotonic() < deadline:
                with urllib.request.urlopen(
                        f"{base}/jobs/{job_id}") as resp:
                    view = json.loads(resp.read())
                state = view["state"]
                if state in ("done", "failed", "cancelled"):
                    break
                time.sleep(0.1)
            assert state == "done", view
            assert view["result"]["unique_state_count"] == 288

            with urllib.request.urlopen(f"{base}/jobs") as resp:
                listing = json.loads(resp.read())
            assert any(j["id"] == job_id for j in listing["jobs"])
            assert listing["profile"]["jobs_done"] >= 1

            with urllib.request.urlopen(
                    f"{base}/jobs/{job_id}/metrics") as resp:
                metrics = json.loads(resp.read())
            assert metrics["profile"].get("chunks", 0) >= 1

            # finished job: SSE replays the recorded trace and ends
            with urllib.request.urlopen(
                    f"{base}/jobs/{job_id}/events", timeout=10) as resp:
                sse = resp.read().decode()
            events = [json.loads(line[6:])
                      for line in sse.splitlines()
                      if line.startswith("data: ")]
            assert any(e["ev"] == "done" for e in events)

            # unknown job -> 404; bad submit -> 400
            for url, data in ((f"{base}/jobs/nope", None),
                              (f"{base}/jobs", b"{}")):
                try:
                    urllib.request.urlopen(urllib.request.Request(
                        url, data=data,
                        headers={"Content-Type": "application/json"}
                        if data else {}))
                    raise AssertionError("expected an HTTP error")
                except urllib.error.HTTPError as exc:
                    assert exc.code in (400, 404)
        finally:
            handle.shutdown()

        # the service's own trace validates against the event schema
        # and records the whole lifecycle
        service_events = []
        with open(os.path.join(tmp_path, "service.jsonl")) as f:
            for line in f:
                if line.strip():
                    service_events.append(json.loads(line))
        # the full SLO lifecycle (PR 14): submit -> grant -> start ->
        # first-chunk -> done
        assert [e["ev"] for e in service_events
                if e["ev"].startswith("job_")] == \
            ["job_submit", "job_grant", "job_start",
             "job_first_chunk", "job_done"]
        for ev in service_events:
            validate_event(ev)
            assert ev["engine"] == "service"

        # tools/trace_report.py --job renders both the job directory
        # and the service root without errors
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "trace_report", os.path.join(REPO, "tools",
                                         "trace_report.py"))
        trace_report = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(trace_report)
        job_dir = os.path.join(tmp_path, job_id)
        import contextlib
        import io
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = trace_report.main(["--job", os.fspath(tmp_path),
                                    "--validate"])
        assert rc == 0
        assert "jobs:" in out.getvalue()
        assert "job_submit" in out.getvalue() \
            or "submit" in out.getvalue()
        located = trace_report.job_traces(job_dir)
        assert any(p.endswith("trace.jsonl") for p in located)


# --- restart survival (subprocess, SIGKILL) ----------------------------

class TestServiceRestart:
    def _serve(self, root, env):
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tools", "jobs.py"),
             "serve", "--root", os.fspath(root), "--cpu",
             "--cpu-devices", "2"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=REPO)
        line = proc.stdout.readline()
        assert "jobs-service listening on" in line, (
            line, proc.stderr.read() if proc.poll() is not None else "")
        url = [tok for tok in line.split() if tok.startswith("http")][0]
        return proc, url

    @pytest.mark.slow
    def test_sigkill_midrun_resumes_to_identical_fingerprints(
            self, tmp_path, solo_2pc4):
        # ACCEPTANCE: service killed -9 mid-run; on the next boot the
        # RUNNING job resumes from its last autosave and finishes with
        # the identical fingerprint set
        # (-m slow since round 11: the second-slowest service pin; the
        # in-process restart-resume parity pin — pause_restart_resume
        # — keeps boot recovery in tier-1, and the batch storm pin
        # needed the budget headroom)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # the serve --cpu flags rebuild it
        root = tmp_path / "svc"
        proc, url = self._serve(root, env)
        try:
            body = json.dumps({
                "model": "twopc", "args": [4],
                "options": {**OPTS, "chunk_steps": 1,
                            "autosave_interval": 1},
                "step_delay": 0.3}).encode()
            req = urllib.request.Request(
                f"{url}/jobs", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                job_id = json.loads(resp.read())["id"]
            # wait until it is RUNNING with an autosave on disk, then
            # kill the whole service dead
            autosave = root / job_id / "autosave.npz"
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                with urllib.request.urlopen(
                        f"{url}/jobs/{job_id}", timeout=10) as resp:
                    state = json.loads(resp.read())["state"]
                if state == "running" and autosave.exists():
                    break
                assert state not in ("done", "failed"), state
                time.sleep(0.05)
            else:
                pytest.fail("job never reached running+autosave")
        finally:
            proc.kill()  # SIGKILL: no cleanup, no checkpoint-on-exit
            proc.wait()

        # boot a fresh service on the same root: the RUNNING job must
        # re-enqueue and resume from the autosave
        proc2, url2 = self._serve(root, env)
        try:
            deadline = time.monotonic() + 180
            state = None
            while time.monotonic() < deadline:
                with urllib.request.urlopen(
                        f"{url2}/jobs/{job_id}", timeout=10) as resp:
                    view = json.loads(resp.read())
                state = view["state"]
                if state in ("done", "failed", "cancelled"):
                    break
                time.sleep(0.2)
            assert state == "done", view
            assert view.get("resume") is True  # it RESUMED, not re-ran
            result = view["result"]
            assert result["unique_state_count"] == \
                solo_2pc4.unique_state_count()
            assert result["fingerprints_sha256"] == _digest(solo_2pc4)
        finally:
            proc2.send_signal(signal.SIGTERM)
            try:
                proc2.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc2.kill()
                proc2.wait()


# --- bench contract ----------------------------------------------------

class TestBenchServiceSmoke:
    def test_contract_line_lands_rc0(self):
        # ACCEPTANCE: --service-smoke lands a contract line, rc=0,
        # with no JAX devices beyond CPU
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--service-smoke"],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        contract = json.loads(proc.stdout.strip().splitlines()[-1])
        assert contract["service"] is True
        assert contract["unit"] == "uniq/s"
        assert "jobs" in contract
        if "partial" not in contract:
            assert contract["value"] and contract["value"] > 0
            assert len(contract["jobs"]) == 2
            assert all(row["state"] == "done"
                       for row in contract["jobs"])
        # tools/bench_history.py understands the service tag
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                "bench_history", os.path.join(REPO, "tools",
                                              "bench_history.py"))
            bh = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(bh)
        finally:
            sys.path.pop(0)
        import tempfile
        with tempfile.TemporaryDirectory() as tdir:
            art = os.path.join(tdir, "BENCH_r99.json")
            with open(art, "w") as f:
                json.dump({"rc": 0, "parsed": contract, "tail": ""}, f)
            report = bh.build_report([art])
        entry = report["trend"][bh.CONTRACT][0]
        assert "service" in entry["tags"]


class TestBenchFlexSmoke:
    @pytest.mark.slow
    def test_contract_line_lands_rc0(self):
        # ACCEPTANCE (elastic fleet): --flex-smoke runs the rolling
        # join -> in-place promote -> pressure -> leave storyline and
        # ALWAYS lands a JSON contract line, rc=0; a full (non-partial)
        # round pins digest parity and bounded promote/demote churn
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--flex-smoke"],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        contract = json.loads(proc.stdout.strip().splitlines()[-1])
        assert contract["flex"] is True
        assert contract["unit"] == "uniq/s"
        if "partial" not in contract:
            assert contract["value"] and contract["value"] > 0
            assert contract["promotes"] >= 1  # the join was USED
            assert contract["promotes"] <= 8  # ... without thrashing
            assert contract["demotes"] <= 8
            assert all(row["digest_ok"] for row in contract["jobs"])
        # tools/bench_history.py tags the flex round
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bench_history", os.path.join(REPO, "tools",
                                          "bench_history.py"))
        bh = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bh)
        import tempfile
        with tempfile.TemporaryDirectory() as tdir:
            art = os.path.join(tdir, "BENCH_r98.json")
            with open(art, "w") as f:
                json.dump({"rc": 0, "parsed": contract, "tail": ""}, f)
            report = bh.build_report([art])
        entry = report["trend"][bh.CONTRACT][0]
        assert "flex" in entry["tags"]
