"""Multi-process host DFS tests (`threads(n).spawn_dfs()`): set-equality
and verdict parity with the sequential DFS across model families —
mirroring the reference's multithreaded DFS promises (`dfs.rs:76-159`,
sharing `dfs.rs:145-157`). Parallel runs cannot pin visitation order, so
assertions use unique counts + fingerprint-set equality, as the
reference's own multithreaded runs require."""

import pytest

from stateright_tpu.actor.test_util import PingPongCfg
from stateright_tpu.models.fixtures import LinearEquation
from stateright_tpu.models.twopc import TwoPhaseSys


def par(model, n=4, **kw):
    ck = model.checker().threads(n)
    for k, v in kw.items():
        getattr(ck, k)(v)
    return ck.spawn_dfs().join()


class TestParallelDfs:
    def test_full_enumeration_matches_sequential(self):
        p = par(TwoPhaseSys(5))  # 8,832 (2pc.rs:133)
        s = TwoPhaseSys(5).checker().spawn_dfs().join()
        assert p.unique_state_count() == 8832
        assert p.generated_fingerprints() == s.generated_fingerprints()

    def test_discovery_replays(self):
        # discoveries carry whole fingerprint paths (dfs.rs:26); an
        # invalid path would fail Path.from_fingerprints replay
        p = par(LinearEquation(2, 10, 14))
        found = p.assert_any_discovery("solvable")
        x, y = found.last_state()
        assert (2 * x + 10 * y) & 0xFF == 14

    def test_actor_model_counts(self):
        model = PingPongCfg(maintains_history=False,
                            max_nat=5).into_model()
        p = par(model)
        s = (PingPongCfg(maintains_history=False, max_nat=5).into_model()
             .checker().spawn_dfs().join())
        assert p.unique_state_count() == 11
        assert set(p.discoveries()) == set(s.discoveries())

    def test_symmetry_reduction(self):
        # the parallel DFS preserves the canonicalize-then-hash-but-
        # enqueue-original rule. The reference representative breaks
        # ties by original position, so the reduced count is
        # exploration-order-specific: the SEQUENTIAL DFS pins the
        # reference's 665 (2pc.rs:138), but racing workers interleave
        # nondeterministically — any count in the sound range
        # [314 true orbits, 1092 distinct representative keys]
        # (brute-forced in NOTES.md) is a correct reduction
        p = par(TwoPhaseSys(5), symmetry_fn=lambda s:
                TwoPhaseSys(5).representative(s))
        assert 314 <= p.unique_state_count() <= 1092, \
            p.unique_state_count()
        p.assert_properties()
        # the orbit-invariant representative is order-independent:
        # every engine, any interleaving, exactly 314
        m = TwoPhaseSys(5, complete_symmetry=True)
        p2 = par(m, symmetry_fn=m.representative)
        assert p2.unique_state_count() == 314
        p2.assert_properties()

    def test_target_state_count(self):
        p = par(LinearEquation(2, 4, 7), target_state_count=500)
        assert p.state_count() >= 500

    def test_visitor_falls_back_to_sequential(self):
        from stateright_tpu.checker.dfs import DfsChecker
        from stateright_tpu.checker.visitor import StateRecorder
        ck = (LinearEquation(2, 10, 14).checker().threads(4)
              .visitor(StateRecorder()).spawn_dfs())
        assert isinstance(ck, DfsChecker)

    @pytest.mark.slow
    def test_full_linear_equation(self):
        # 65,536-state full enumeration across 4 workers
        # (-m slow since round 11: at ~180s this single host-engine
        # scale pin was >20% of the tier-1 budget; the parity /
        # discovery / symmetry / shared-insert pins above keep the
        # multi-process DFS machinery fully covered in tier-1, and the
        # batch-lane storm pin needed the headroom)
        p = par(LinearEquation(2, 4, 251))
        s = LinearEquation(2, 4, 251).checker().spawn_dfs().join()
        assert (p.unique_state_count() == s.unique_state_count()
                == 65536)


def test_threads_after_xla_initialized():
    # the forkserver never inherits this process's native threads, so a
    # multi-process checker constructed AFTER XLA spun up its threadpool
    # in-process must work (the old fork()-based pool was fork-unsafe
    # here per POSIX)
    import jax.numpy as jnp

    (jnp.zeros((8,)) + 1).sum().item()  # force backend + threadpool init
    p = par(TwoPhaseSys(3))
    assert p.unique_state_count() == 288
    b = TwoPhaseSys(3).checker().threads(2).spawn_bfs().join()
    assert b.unique_state_count() == 288


def test_no_fork_deprecation_warning(recwarn):
    # the multi-process engines use forkserver + cloudpickle: no
    # fork()-with-threads DeprecationWarning may escape
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error", DeprecationWarning)
        p = par(TwoPhaseSys(3))
        assert p.unique_state_count() == 288
        b = TwoPhaseSys(3).checker().threads(2).spawn_bfs().join()
        assert b.unique_state_count() == 288


def test_shared_insert_zero_fingerprint_and_no_lost_updates():
    """fp=0 collides with the empty-slot sentinel and is remapped to 1
    (advisor r3, low); the striped-lock store means a claimed fp is
    never lost to a concurrent overwrite (advisor r3, medium)."""
    import threading

    import numpy as np

    from stateright_tpu.checker.parallel_dfs import (_N_STRIPES,
                                                     _shared_insert)

    table = np.zeros((64,), dtype=np.uint64)
    locks = [threading.Lock() for _ in range(_N_STRIPES)]
    # fp 0 claims once (as the reserved value 1), then dedups
    assert _shared_insert(table, 63, 0, locks)
    assert not _shared_insert(table, 63, 0, locks)
    assert not _shared_insert(table, 63, 1, locks)  # documented merge
    # hammer the table from threads with overlapping fp sets: every fp
    # must still be present at the end (no lost updates)
    fps = list(range(2, 40))
    def worker():
        for fp in fps:
            _shared_insert(table, 63, fp, locks)
    ts = [threading.Thread(target=worker) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    present = set(int(v) for v in np.unique(table[table != 0]))
    assert set(fps) <= present
