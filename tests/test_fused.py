"""Fused Pallas expand→fingerprint→dedup kernel (ops/fused.py).

Parity is pinned on CPU through Pallas **interpret mode**
(``tpu_options(fused=True)`` resolves to the interpreter off TPU), so
tier-1 verifies bit-identical behavior — same discovery sets, same
visited-fingerprint sets, same unique counts — without hardware. The
``fused='auto'`` contract (attempt → classified fallback → staged run,
never a hard error) is pinned by monkeypatching the build probe.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402


def _run(model, fused, **opts):
    return (model.checker()
            .tpu_options(race=False, fused=fused, **opts)
            .spawn_tpu().join())


@pytest.fixture(scope="module")
def host_2pc3():
    model = TwoPhaseSys(3)
    return model.checker().spawn_bfs().join()


class TestFusedParity:
    def test_2pc_full_parity(self, host_2pc3):
        # full enumeration: the fused kernel must reproduce the staged
        # path's reached set, discoveries and counts exactly (2pc n=3:
        # 288 unique, `2pc.rs:128`). cc_dedup=False isolates the
        # kernel itself so even the probe-round telemetry is
        # bit-identical (the ring legitimately SHRINKS probe rounds —
        # its own pins live in TestCcDedup)
        staged = _run(TwoPhaseSys(3), False, capacity=1 << 12, fmax=64)
        fused = _run(TwoPhaseSys(3), True, capacity=1 << 12, fmax=64,
                     cc_dedup=False)
        assert staged.unique_state_count() == 288
        assert fused.unique_state_count() == 288
        assert (fused.generated_fingerprints()
                == staged.generated_fingerprints()
                == host_2pc3.generated_fingerprints())
        assert set(fused.discoveries()) == set(staged.discoveries())
        # the dedup telemetry rides both paths and must agree on this
        # deterministic workload; the path tag must not
        ps, pf = staged.profile(), fused.profile()
        assert ps["fused"] == 0 and pf["fused"] == 1
        assert pf["fused_chunks"] == pf["chunks"] > 0
        assert pf["predup_hits"] == ps["predup_hits"] > 0
        assert pf["probe_rounds"] == ps["probe_rounds"] > 0
        assert not pf.get("cc_dedup_hits")

    def test_discovery_paths_replay_fused(self):
        # mirror integrity: witness reconstruction through the fused
        # path's (fp -> parent fp) log must replay real transitions
        # (the witnesses are now selected by the IN-KERNEL property
        # eval — the sticky per-block registers)
        model = TwoPhaseSys(3)
        fused = _run(model, True, capacity=1 << 12, fmax=64)
        for name, path in fused.discoveries().items():
            prop = model.property(name)
            assert prop.condition(model, path.last_state())

    @pytest.mark.slow
    def test_sharded_parity(self, host_2pc3):
        # sharded engines fuse up to the exchange boundary; reached
        # sets and discoveries must match host BFS across the D=2 mesh
        from jax.sharding import Mesh
        devices = jax.devices()
        if len(devices) < 2:
            pytest.skip("need 2 devices")
        mesh = Mesh(np.array(devices[:2]), ("shards",))
        staged = _run(TwoPhaseSys(3), False, mesh=mesh,
                      capacity=1 << 12, fmax=64)
        fused = _run(TwoPhaseSys(3), True, mesh=mesh,
                     capacity=1 << 12, fmax=64)
        assert fused.unique_state_count() == 288
        assert (fused.generated_fingerprints()
                == staged.generated_fingerprints()
                == host_2pc3.generated_fingerprints())
        assert set(fused.discoveries()) == set(staged.discoveries())
        assert fused.profile()["fused_chunks"] > 0

    @pytest.mark.slow
    def test_symmetry_parity(self):
        # Increment's representative is value-complete (full-word
        # sort), so reduced counts are engine-independent — the fused
        # which-duplicate-wins race cannot move them
        from stateright_tpu.examples.increment import Increment
        model = Increment(2)
        staged = (model.checker().symmetry_fn(model.representative)
                  .tpu_options(race=False, fused=False,
                               capacity=1 << 12)
                  .spawn_tpu().join())
        model2 = Increment(2)
        fused = (model2.checker().symmetry_fn(model2.representative)
                 .tpu_options(race=False, fused=True, capacity=1 << 12)
                 .spawn_tpu().join())
        assert (fused.unique_state_count()
                == staged.unique_state_count())
        assert (fused.generated_fingerprints()
                == staged.generated_fingerprints())
        assert set(fused.discoveries()) == set(staged.discoveries())

    @pytest.mark.slow
    @pytest.mark.faults
    def test_crash_restart_parity(self):
        # packed crash-nibble lanes ride packed_step, so the kernel
        # (which vmaps packed_step) covers fault injection for free —
        # pin it against host BFS. (The write-once/paxos crash models
        # declare host-evaluated properties, which the fused path does
        # not cover — see supports(); PackedTimerCount is pure-device.)
        from stateright_tpu.actor.test_util import PackedTimerCount

        def mk():
            return PackedTimerCount(2, 2).crash_restart(2)

        host = mk().checker().spawn_bfs().join()
        fused = _run(mk(), True, capacity=1 << 14)
        assert (host.unique_state_count() == fused.unique_state_count()
                == 49)
        assert (host.generated_fingerprints()
                == fused.generated_fingerprints())
        assert set(fused.discoveries()) == set(host.discoveries())

    @pytest.mark.slow
    def test_growth_preserves_enumeration_fused(self):
        # mid-run table growth rebuilds the fused chunk program at the
        # new capacity (fresh kernel shapes) — enumeration must survive
        model = TwoPhaseSys(5)
        fused = _run(model, True, capacity=1 << 12, fmax=32)
        assert fused.profile().get("grows", 0) > 0
        assert fused.unique_state_count() == 8832
        host = model.checker().spawn_bfs().join()
        assert (fused.generated_fingerprints()
                == host.generated_fingerprints())


class TestFusedSelection:
    def test_auto_on_cpu_stays_staged(self):
        # off-TPU, 'auto' resolves to staged with no attempt and no
        # fallback event — the interpreter would be slower than XLA
        trace = []
        ck = _run(TwoPhaseSys(3), "auto", capacity=1 << 12,
                  trace=trace)
        assert ck.unique_state_count() == 288
        assert ck.profile()["fused"] == 0
        assert not ck.profile().get("fused_fallbacks")
        assert not [e for e in trace if e["ev"] == "fused_fallback"]

    def test_auto_fallback_classified_never_hard_errors(self,
                                                       monkeypatch):
        # the 'auto' contract: a failing Pallas build (the experimental
        # `axon` backend's expected mode) is classified via the
        # resilience taxonomy, traced, counted — and the run completes
        # on the staged path with identical results
        from stateright_tpu.ops import fused as fused_mod

        def boom(*a, **k):
            raise RuntimeError(
                "UNAVAILABLE: mosaic lowering not supported on this "
                "backend (injected)")

        monkeypatch.setattr(fused_mod, "verify_build", boom)
        trace = []
        ck = _run(TwoPhaseSys(3), "auto", fused_attempt=True,
                  capacity=1 << 12, trace=trace)
        assert ck.unique_state_count() == 288
        prof = ck.profile()
        assert prof["fused"] == 0
        assert prof["fused_fallbacks"] == 1
        events = [e for e in trace if e["ev"] == "fused_fallback"]
        assert len(events) == 1
        assert events[0]["cause"] == "transient"
        assert "UNAVAILABLE" in events[0]["error"]

    def test_forced_fused_unsupported_raises(self):
        # fused=True is an explicit instruction: a configuration the
        # kernel cannot cover must fail loudly, not silently downgrade
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, fused=True, hint=2,
                           capacity=1 << 12)
              .spawn_tpu())
        with pytest.raises(ValueError, match="fused=True"):
            ck.join()

    def test_unknown_fused_value_rejected(self):
        with pytest.raises(ValueError, match="fused"):
            (TwoPhaseSys(3).checker()
             .tpu_options(race=False, fused="maybe")
             .spawn_tpu())

    def test_verify_build_memoizes_failure(self):
        # a known-bad build must not re-pay the attempt every run: the
        # memo replays the failure as FusedUnavailable
        from stateright_tpu.ops import fused as fused_mod
        model = TwoPhaseSys(3)
        probe = dict(symmetry=False, probe=True, interpret=True)

        calls = []
        orig = fused_mod.build_fused_block_fn

        def counting(*a, **k):
            calls.append(1)
            raise RuntimeError("UNAVAILABLE: injected build failure")

        try:
            fused_mod.build_fused_block_fn = counting
            with pytest.raises(RuntimeError, match="UNAVAILABLE"):
                fused_mod.verify_build(model, 32, 1 << 10, **probe)
            with pytest.raises(fused_mod.FusedUnavailable,
                               match="UNAVAILABLE"):
                fused_mod.verify_build(model, 32, 1 << 10, **probe)
            assert len(calls) == 1
        finally:
            fused_mod.build_fused_block_fn = orig


class TestInKernelProps:
    """Property-predicate evaluation fused INTO the step kernel: the
    per-block sticky (hit, witness fp) registers must reproduce the
    staged path's discovery selection exactly — same properties, same
    witness paths, not just the same names."""

    def test_witness_replay_identical_to_staged(self, host_2pc3):
        model = TwoPhaseSys(3)
        staged = _run(TwoPhaseSys(3), False, capacity=1 << 12, fmax=64)
        fused = _run(model, True, capacity=1 << 12, fmax=64)
        assert set(fused.discoveries()) == set(staged.discoveries())
        for name, path in fused.discoveries().items():
            # identical witness REPLAY: the same state sequence, ending
            # in a state that really satisfies/violates the property
            assert (path.into_states()
                    == staged.discoveries()[name].into_states()), name
            assert model.property(name).condition(model,
                                                  path.last_state())

    def test_eventually_terminal_flush_in_kernel(self):
        # EVENTUALLY discoveries come from the terminal-flush mask
        # (terminal rows with pending ebits) — evaluated in-kernel too
        from stateright_tpu.actor.test_util import PackedTimerCount
        host = PackedTimerCount(2, 2).checker().spawn_bfs().join()
        fused = _run(PackedTimerCount(2, 2), True, capacity=1 << 12)
        assert set(fused.discoveries()) == set(host.discoveries())
        assert (fused.generated_fingerprints()
                == host.generated_fingerprints())


class TestShardedProbeKernel:
    """The sharded fused pipeline's SECOND Pallas kernel: the owner-side
    post-exchange probe/insert (previously a staged program between the
    all-to-all and the append) must be digest-identical to the staged
    path on every mesh width and both exchanges."""

    @staticmethod
    def _mesh(d):
        from jax.sharding import Mesh
        devices = jax.devices()
        if len(devices) < d:
            pytest.skip(f"need {d} devices")
        return Mesh(np.array(devices[:d]), ("shards",))

    def _digest(self, ck):
        import hashlib
        fps = sorted(ck.generated_fingerprints())
        return hashlib.sha256(
            ",".join(str(f) for f in fps).encode()).hexdigest()

    def test_d2_bucket_digest_identical_to_staged(self, host_2pc3):
        mesh = self._mesh(2)
        staged = _run(TwoPhaseSys(3), False, mesh=mesh,
                      capacity=1 << 12, fmax=64)
        fused = _run(TwoPhaseSys(3), True, mesh=mesh,
                     capacity=1 << 12, fmax=64)
        assert fused.unique_state_count() == 288
        assert self._digest(fused) == self._digest(staged) \
            == self._digest(host_2pc3)
        assert set(fused.discoveries()) == set(staged.discoveries())
        # probe telemetry rides the second kernel's flags
        assert fused.profile()["probe_rounds"] > 0

    @pytest.mark.slow
    def test_d4_digest_identical_to_staged(self, host_2pc3):
        mesh = self._mesh(4)
        staged = _run(TwoPhaseSys(3), False, mesh=mesh,
                      capacity=1 << 12, fmax=64)
        fused = _run(TwoPhaseSys(3), True, mesh=mesh,
                     capacity=1 << 12, fmax=64)
        assert self._digest(fused) == self._digest(staged) \
            == self._digest(host_2pc3)

    @pytest.mark.slow
    def test_d2_ring_exchange_probe_kernel(self, host_2pc3):
        # the ring exchange probes per hop — the kernel replaces every
        # hop's staged insert
        mesh = self._mesh(2)
        fused = _run(TwoPhaseSys(3), True, mesh=mesh, exchange="ring",
                     capacity=1 << 12, fmax=64)
        assert self._digest(fused) == self._digest(host_2pc3)


class TestCcDedup:
    """Cross-chunk in-kernel dedup ring (`tpu_options(cc_dedup=...)`):
    soundness property — the cache may only kill lanes whose
    fingerprint already committed to the visited set, so the enumerated
    fingerprint set, unique counts and discoveries are IDENTICAL to the
    staged path (a false miss only costs a table probe, never drops a
    fresh key — `pre_dedup`'s argument, one tier up)."""

    def test_never_drops_fresh_fingerprint_2pc(self, host_2pc3):
        staged = _run(TwoPhaseSys(3), False, capacity=1 << 12, fmax=64)
        fused = _run(TwoPhaseSys(3), True, capacity=1 << 12, fmax=64)
        assert fused.unique_state_count() == 288
        assert (fused.generated_fingerprints()
                == staged.generated_fingerprints()
                == host_2pc3.generated_fingerprints())
        assert set(fused.discoveries()) == set(staged.discoveries())
        pf, ps = fused.profile(), staged.profile()
        # the ring actually fired on this duplicate-heavy model, the
        # in-batch share stayed exact, and ring kills can only REDUCE
        # table probe pressure
        assert pf["cc_dedup_hits"] > 0
        assert pf["cc_dedup_capacity"] > 0
        assert pf["predup_hits"] == ps["predup_hits"]
        assert pf["probe_rounds"] <= ps["probe_rounds"]
        # generated counts are pre-dedup semantics: untouched by cc
        assert fused.state_count() == staged.state_count()

    def test_sharded_cc_kills_before_exchange(self, host_2pc3):
        from jax.sharding import Mesh
        devices = jax.devices()
        if len(devices) < 2:
            pytest.skip("need 2 devices")
        mesh = Mesh(np.array(devices[:2]), ("shards",))
        fused = _run(TwoPhaseSys(3), True, mesh=mesh,
                     capacity=1 << 12, fmax=64)
        assert (fused.generated_fingerprints()
                == host_2pc3.generated_fingerprints())
        assert fused.profile()["cc_dedup_hits"] > 0

    def test_cc_option_validation(self):
        with pytest.raises(ValueError, match="cc_dedup"):
            (TwoPhaseSys(3).checker()
             .tpu_options(race=False, cc_dedup=1000)  # not a pow2
             .spawn_tpu())

    def test_custom_ring_size(self, host_2pc3):
        # a deliberately TINY ring: heavy slot eviction, so most probes
        # miss — misses must only cost table probes, never keys
        fused = _run(TwoPhaseSys(3), True, capacity=1 << 12, fmax=64,
                     cc_dedup=64)
        assert (fused.generated_fingerprints()
                == host_2pc3.generated_fingerprints())
        assert fused.profile()["cc_dedup_capacity"] == 64

    @pytest.mark.slow
    def test_2pc6_full_parity(self):
        # a bigger duplicate-heavy space (2pc n=6, 35k unique): host
        # oracle + staged + fused-with-ring all agree, and the ring
        # catches a meaningful share of the cross-chunk re-expansion.
        # (paxos models declare the host-evaluated `linearizable`
        # property, which supports() keeps staged — pinned by
        # TestFusedUnsupported::test_paxos_auto_reports_host_props.)
        host = TwoPhaseSys(6).checker().spawn_bfs().join()
        fused = _run(TwoPhaseSys(6), True, capacity=1 << 16, fmax=128)
        assert (fused.generated_fingerprints()
                == host.generated_fingerprints())
        assert set(fused.discoveries()) == set(host.discoveries())
        assert fused.profile()["cc_dedup_hits"] > 0

    @pytest.mark.slow
    @pytest.mark.faults
    def test_crash_restart_cc_parity(self):
        # crash-nibble lanes + the ring: a restart re-reaches earlier
        # states (genuine cross-chunk duplicates) — parity must hold
        from stateright_tpu.actor.test_util import PackedTimerCount

        def mk():
            return PackedTimerCount(2, 2).crash_restart(2)

        host = mk().checker().spawn_bfs().join()
        fused = _run(mk(), True, capacity=1 << 14, cc_dedup=256)
        assert (host.generated_fingerprints()
                == fused.generated_fingerprints())
        assert set(fused.discoveries()) == set(host.discoveries())


class TestFusedUnsupported:
    def test_auto_unsupported_emits_reason_once(self):
        # supports() exclusions no longer "quietly stay staged": one
        # fused_unsupported event names the reason, the gauge rides
        # profile(), and report()'s metrics line renders it
        import io
        trace = []
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, fused="auto", hint=2,
                           capacity=1 << 12, trace=trace)
              .spawn_tpu().join())
        assert ck.unique_state_count() == 288
        prof = ck.profile()
        assert prof["fused"] == 0
        assert prof["fused_unsupported"] == 1
        events = [e for e in trace if e["ev"] == "fused_unsupported"]
        assert len(events) == 1
        assert "hint" in events[0]["reason"]
        out = io.StringIO()
        ck.report(out)
        assert "fused=unsupported" in out.getvalue()

    def test_supported_auto_run_has_no_unsupported_marker(self):
        trace = []
        ck = _run(TwoPhaseSys(3), "auto", capacity=1 << 12,
                  trace=trace)
        assert "fused_unsupported" not in ck.profile()
        assert not [e for e in trace
                    if e["ev"] == "fused_unsupported"]

    def test_paxos_auto_reports_host_props(self):
        # the real-world exclusion: register-protocol models (paxos,
        # abd, single-copy) declare the host-evaluated `linearizable`
        # property — 'auto' stays staged and now SAYS so
        from stateright_tpu.examples.paxos_packed import PackedPaxos
        trace = []
        ck = (PackedPaxos(2).checker()
              .tpu_options(race=False, fused="auto", trace=trace,
                           capacity=1 << 14)
              .target_state_count(2000)
              .spawn_tpu().join())
        events = [e for e in trace if e["ev"] == "fused_unsupported"]
        assert len(events) == 1
        assert "host-evaluated" in events[0]["reason"]
        assert ck.profile()["fused_unsupported"] == 1


class TestPreDedupSoundness:
    """`ops.expand.pre_dedup` arena-collision property: a lane is ONLY
    dropped when an earlier valid lane carries the SAME fingerprint —
    distinct keys colliding on an arena cell must both survive, so the
    retained fingerprint SET always equals the valid input set."""

    @staticmethod
    def _check(chi, clo, cvalid):
        import jax.numpy as jnp

        from stateright_tpu.ops.expand import pre_dedup
        keep = np.asarray(pre_dedup(jnp.asarray(chi), jnp.asarray(clo),
                                    jnp.asarray(cvalid)))
        fps = [(int(h), int(l)) for h, l in zip(chi, clo)]
        valid_set = {fp for fp, v in zip(fps, cvalid) if v}
        kept_set = {fp for fp, k in zip(fps, keep) if k}
        # soundness: no fingerprint vanishes, no invalid lane appears
        assert kept_set == valid_set
        # a dropped lane always has an EARLIER kept duplicate
        for i, (fp, v) in enumerate(zip(fps, cvalid)):
            if v and not keep[i]:
                assert any(keep[j] and fps[j] == fp for j in range(i))
        return keep

    def test_random_batches(self):
        rng = np.random.default_rng(7)
        for n in (8, 64, 257):
            chi = rng.integers(0, 2**32, n, dtype=np.uint32)
            clo = rng.integers(0, 2**32, n, dtype=np.uint32)
            # force heavy duplication: sample lanes from few keys
            pick = rng.integers(0, max(n // 4, 1), n)
            chi, clo = chi[pick], clo[pick]
            cvalid = rng.random(n) < 0.8
            self._check(chi, clo, cvalid)

    def test_engineered_arena_collisions(self):
        # distinct keys crafted onto the SAME arena cell: slot is
        # (clo ^ chi*PHI) & (acells-1) with acells = 2^ceil(log2(2n)),
        # so with chi=0, clo values differing only above the mask bits
        # collide. Both must be kept (dropping either would lose a
        # unique state — unsound).
        n = 8
        acells = 1 << max((2 * n - 1).bit_length(), 0)
        chi = np.zeros(n, np.uint32)
        clo = (np.arange(n, dtype=np.uint32) * np.uint32(acells)
               + np.uint32(3))  # all lanes -> arena cell 3
        cvalid = np.ones(n, bool)
        keep = self._check(chi, clo, cvalid)
        assert keep.all()  # distinct keys: nothing may be dropped

    def test_collision_with_duplicates_mixed(self):
        # colliding distinct keys on one cell (all must survive — a
        # collision loser is only dropped when the winner VERIFIES
        # equal) plus a true-duplicate pair alone on another cell
        # (the later lane dies in favor of the earlier). Duplicates
        # hiding behind a foreign collision winner survive pre-dedup —
        # that's the documented soundness trade; the table probe
        # resolves them.
        n = 16
        acells = 1 << max((2 * n - 1).bit_length(), 0)
        chi = np.zeros(n, np.uint32)
        # lanes 0..11: distinct keys, all on arena cell 3
        clo = (np.arange(n, dtype=np.uint32) * np.uint32(acells)
               + np.uint32(3))
        # lanes 12..15: ONE key on its own cell 7 — true duplicates
        clo[12:] = np.uint32(7)
        cvalid = np.ones(n, bool)
        keep = self._check(chi, clo, cvalid)
        assert keep[:13].all()          # distinct keys + first dup
        assert not keep[13:].any()      # later duplicates die


@pytest.mark.slow
def test_kernel_bench_emits_json(tmp_path):
    # tools/kernel_bench.py: the staged-vs-fused microbenchmark must
    # land parseable per-stage JSON (the PR-report artifact)
    import json
    import subprocess
    import sys
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "kb.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "kernel_bench.py"),
         "--model", "2pc4", "--fmax", "64", "--iters", "2",
         "--out", str(out)],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=repo)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(out.read_text())
    assert line["interpret"] is True
    for key in ("expand_ms", "hash_ms", "pre_dedup_ms", "probe_ms",
                "probe_kernel_ms"):
        assert line["stages"][key] >= 0
    assert line["fused_ms"] > 0 and line["staged_ms"] > 0
    # the sharded two-kernel path (step kernel + owner-side probe
    # kernel, exchange excluded) reports its own composed numbers
    assert line["sharded_fused_ms"] > 0
    assert line["sharded_staged_ms"] > 0
    assert line["sharded_fused_over_staged"] > 0
    assert 0 <= line["dup_lane_frac"] <= 1
