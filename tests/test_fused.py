"""Fused Pallas expand→fingerprint→dedup kernel (ops/fused.py).

Parity is pinned on CPU through Pallas **interpret mode**
(``tpu_options(fused=True)`` resolves to the interpreter off TPU), so
tier-1 verifies bit-identical behavior — same discovery sets, same
visited-fingerprint sets, same unique counts — without hardware. The
``fused='auto'`` contract (attempt → classified fallback → staged run,
never a hard error) is pinned by monkeypatching the build probe.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402


def _run(model, fused, **opts):
    return (model.checker()
            .tpu_options(race=False, fused=fused, **opts)
            .spawn_tpu().join())


@pytest.fixture(scope="module")
def host_2pc3():
    model = TwoPhaseSys(3)
    return model.checker().spawn_bfs().join()


class TestFusedParity:
    def test_2pc_full_parity(self, host_2pc3):
        # full enumeration: the fused kernel must reproduce the staged
        # path's reached set, discoveries and counts exactly (2pc n=3:
        # 288 unique, `2pc.rs:128`)
        staged = _run(TwoPhaseSys(3), False, capacity=1 << 12, fmax=64)
        fused = _run(TwoPhaseSys(3), True, capacity=1 << 12, fmax=64)
        assert staged.unique_state_count() == 288
        assert fused.unique_state_count() == 288
        assert (fused.generated_fingerprints()
                == staged.generated_fingerprints()
                == host_2pc3.generated_fingerprints())
        assert set(fused.discoveries()) == set(staged.discoveries())
        # the dedup telemetry rides both paths and must agree on this
        # deterministic workload; the path tag must not
        ps, pf = staged.profile(), fused.profile()
        assert ps["fused"] == 0 and pf["fused"] == 1
        assert pf["fused_chunks"] == pf["chunks"] > 0
        assert pf["predup_hits"] == ps["predup_hits"] > 0
        assert pf["probe_rounds"] == ps["probe_rounds"] > 0

    def test_discovery_paths_replay_fused(self):
        # mirror integrity: witness reconstruction through the fused
        # path's (fp -> parent fp) log must replay real transitions
        model = TwoPhaseSys(3)
        fused = _run(model, True, capacity=1 << 12, fmax=64)
        for name, path in fused.discoveries().items():
            prop = model.property(name)
            assert prop.condition(model, path.last_state())

    @pytest.mark.slow
    def test_sharded_parity(self, host_2pc3):
        # sharded engines fuse up to the exchange boundary; reached
        # sets and discoveries must match host BFS across the D=2 mesh
        from jax.sharding import Mesh
        devices = jax.devices()
        if len(devices) < 2:
            pytest.skip("need 2 devices")
        mesh = Mesh(np.array(devices[:2]), ("shards",))
        staged = _run(TwoPhaseSys(3), False, mesh=mesh,
                      capacity=1 << 12, fmax=64)
        fused = _run(TwoPhaseSys(3), True, mesh=mesh,
                     capacity=1 << 12, fmax=64)
        assert fused.unique_state_count() == 288
        assert (fused.generated_fingerprints()
                == staged.generated_fingerprints()
                == host_2pc3.generated_fingerprints())
        assert set(fused.discoveries()) == set(staged.discoveries())
        assert fused.profile()["fused_chunks"] > 0

    @pytest.mark.slow
    def test_symmetry_parity(self):
        # Increment's representative is value-complete (full-word
        # sort), so reduced counts are engine-independent — the fused
        # which-duplicate-wins race cannot move them
        from stateright_tpu.examples.increment import Increment
        model = Increment(2)
        staged = (model.checker().symmetry_fn(model.representative)
                  .tpu_options(race=False, fused=False,
                               capacity=1 << 12)
                  .spawn_tpu().join())
        model2 = Increment(2)
        fused = (model2.checker().symmetry_fn(model2.representative)
                 .tpu_options(race=False, fused=True, capacity=1 << 12)
                 .spawn_tpu().join())
        assert (fused.unique_state_count()
                == staged.unique_state_count())
        assert (fused.generated_fingerprints()
                == staged.generated_fingerprints())
        assert set(fused.discoveries()) == set(staged.discoveries())

    @pytest.mark.slow
    @pytest.mark.faults
    def test_crash_restart_parity(self):
        # packed crash-nibble lanes ride packed_step, so the kernel
        # (which vmaps packed_step) covers fault injection for free —
        # pin it against host BFS. (The write-once/paxos crash models
        # declare host-evaluated properties, which the fused path does
        # not cover — see supports(); PackedTimerCount is pure-device.)
        from stateright_tpu.actor.test_util import PackedTimerCount

        def mk():
            return PackedTimerCount(2, 2).crash_restart(2)

        host = mk().checker().spawn_bfs().join()
        fused = _run(mk(), True, capacity=1 << 14)
        assert (host.unique_state_count() == fused.unique_state_count()
                == 49)
        assert (host.generated_fingerprints()
                == fused.generated_fingerprints())
        assert set(fused.discoveries()) == set(host.discoveries())

    @pytest.mark.slow
    def test_growth_preserves_enumeration_fused(self):
        # mid-run table growth rebuilds the fused chunk program at the
        # new capacity (fresh kernel shapes) — enumeration must survive
        model = TwoPhaseSys(5)
        fused = _run(model, True, capacity=1 << 12, fmax=32)
        assert fused.profile().get("grows", 0) > 0
        assert fused.unique_state_count() == 8832
        host = model.checker().spawn_bfs().join()
        assert (fused.generated_fingerprints()
                == host.generated_fingerprints())


class TestFusedSelection:
    def test_auto_on_cpu_stays_staged(self):
        # off-TPU, 'auto' resolves to staged with no attempt and no
        # fallback event — the interpreter would be slower than XLA
        trace = []
        ck = _run(TwoPhaseSys(3), "auto", capacity=1 << 12,
                  trace=trace)
        assert ck.unique_state_count() == 288
        assert ck.profile()["fused"] == 0
        assert not ck.profile().get("fused_fallbacks")
        assert not [e for e in trace if e["ev"] == "fused_fallback"]

    def test_auto_fallback_classified_never_hard_errors(self,
                                                       monkeypatch):
        # the 'auto' contract: a failing Pallas build (the experimental
        # `axon` backend's expected mode) is classified via the
        # resilience taxonomy, traced, counted — and the run completes
        # on the staged path with identical results
        from stateright_tpu.ops import fused as fused_mod

        def boom(*a, **k):
            raise RuntimeError(
                "UNAVAILABLE: mosaic lowering not supported on this "
                "backend (injected)")

        monkeypatch.setattr(fused_mod, "verify_build", boom)
        trace = []
        ck = _run(TwoPhaseSys(3), "auto", fused_attempt=True,
                  capacity=1 << 12, trace=trace)
        assert ck.unique_state_count() == 288
        prof = ck.profile()
        assert prof["fused"] == 0
        assert prof["fused_fallbacks"] == 1
        events = [e for e in trace if e["ev"] == "fused_fallback"]
        assert len(events) == 1
        assert events[0]["cause"] == "transient"
        assert "UNAVAILABLE" in events[0]["error"]

    def test_forced_fused_unsupported_raises(self):
        # fused=True is an explicit instruction: a configuration the
        # kernel cannot cover must fail loudly, not silently downgrade
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, fused=True, hint=2,
                           capacity=1 << 12)
              .spawn_tpu())
        with pytest.raises(ValueError, match="fused=True"):
            ck.join()

    def test_unknown_fused_value_rejected(self):
        with pytest.raises(ValueError, match="fused"):
            (TwoPhaseSys(3).checker()
             .tpu_options(race=False, fused="maybe")
             .spawn_tpu())

    def test_verify_build_memoizes_failure(self):
        # a known-bad build must not re-pay the attempt every run: the
        # memo replays the failure as FusedUnavailable
        from stateright_tpu.ops import fused as fused_mod
        model = TwoPhaseSys(3)
        probe = dict(symmetry=False, probe=True, interpret=True)

        calls = []
        orig = fused_mod.build_fused_block_fn

        def counting(*a, **k):
            calls.append(1)
            raise RuntimeError("UNAVAILABLE: injected build failure")

        try:
            fused_mod.build_fused_block_fn = counting
            with pytest.raises(RuntimeError, match="UNAVAILABLE"):
                fused_mod.verify_build(model, 32, 1 << 10, **probe)
            with pytest.raises(fused_mod.FusedUnavailable,
                               match="UNAVAILABLE"):
                fused_mod.verify_build(model, 32, 1 << 10, **probe)
            assert len(calls) == 1
        finally:
            fused_mod.build_fused_block_fn = orig


class TestPreDedupSoundness:
    """`ops.expand.pre_dedup` arena-collision property: a lane is ONLY
    dropped when an earlier valid lane carries the SAME fingerprint —
    distinct keys colliding on an arena cell must both survive, so the
    retained fingerprint SET always equals the valid input set."""

    @staticmethod
    def _check(chi, clo, cvalid):
        import jax.numpy as jnp

        from stateright_tpu.ops.expand import pre_dedup
        keep = np.asarray(pre_dedup(jnp.asarray(chi), jnp.asarray(clo),
                                    jnp.asarray(cvalid)))
        fps = [(int(h), int(l)) for h, l in zip(chi, clo)]
        valid_set = {fp for fp, v in zip(fps, cvalid) if v}
        kept_set = {fp for fp, k in zip(fps, keep) if k}
        # soundness: no fingerprint vanishes, no invalid lane appears
        assert kept_set == valid_set
        # a dropped lane always has an EARLIER kept duplicate
        for i, (fp, v) in enumerate(zip(fps, cvalid)):
            if v and not keep[i]:
                assert any(keep[j] and fps[j] == fp for j in range(i))
        return keep

    def test_random_batches(self):
        rng = np.random.default_rng(7)
        for n in (8, 64, 257):
            chi = rng.integers(0, 2**32, n, dtype=np.uint32)
            clo = rng.integers(0, 2**32, n, dtype=np.uint32)
            # force heavy duplication: sample lanes from few keys
            pick = rng.integers(0, max(n // 4, 1), n)
            chi, clo = chi[pick], clo[pick]
            cvalid = rng.random(n) < 0.8
            self._check(chi, clo, cvalid)

    def test_engineered_arena_collisions(self):
        # distinct keys crafted onto the SAME arena cell: slot is
        # (clo ^ chi*PHI) & (acells-1) with acells = 2^ceil(log2(2n)),
        # so with chi=0, clo values differing only above the mask bits
        # collide. Both must be kept (dropping either would lose a
        # unique state — unsound).
        n = 8
        acells = 1 << max((2 * n - 1).bit_length(), 0)
        chi = np.zeros(n, np.uint32)
        clo = (np.arange(n, dtype=np.uint32) * np.uint32(acells)
               + np.uint32(3))  # all lanes -> arena cell 3
        cvalid = np.ones(n, bool)
        keep = self._check(chi, clo, cvalid)
        assert keep.all()  # distinct keys: nothing may be dropped

    def test_collision_with_duplicates_mixed(self):
        # colliding distinct keys on one cell (all must survive — a
        # collision loser is only dropped when the winner VERIFIES
        # equal) plus a true-duplicate pair alone on another cell
        # (the later lane dies in favor of the earlier). Duplicates
        # hiding behind a foreign collision winner survive pre-dedup —
        # that's the documented soundness trade; the table probe
        # resolves them.
        n = 16
        acells = 1 << max((2 * n - 1).bit_length(), 0)
        chi = np.zeros(n, np.uint32)
        # lanes 0..11: distinct keys, all on arena cell 3
        clo = (np.arange(n, dtype=np.uint32) * np.uint32(acells)
               + np.uint32(3))
        # lanes 12..15: ONE key on its own cell 7 — true duplicates
        clo[12:] = np.uint32(7)
        cvalid = np.ones(n, bool)
        keep = self._check(chi, clo, cvalid)
        assert keep[:13].all()          # distinct keys + first dup
        assert not keep[13:].any()      # later duplicates die


@pytest.mark.slow
def test_kernel_bench_emits_json(tmp_path):
    # tools/kernel_bench.py: the staged-vs-fused microbenchmark must
    # land parseable per-stage JSON (the PR-report artifact)
    import json
    import subprocess
    import sys
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "kb.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "kernel_bench.py"),
         "--model", "2pc4", "--fmax", "64", "--iters", "2",
         "--out", str(out)],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=repo)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(out.read_text())
    assert line["interpret"] is True
    for key in ("expand_ms", "hash_ms", "pre_dedup_ms", "probe_ms"):
        assert line["stages"][key] >= 0
    assert line["fused_ms"] > 0 and line["staged_ms"] > 0
    assert 0 <= line["dup_lane_frac"] <= 1
