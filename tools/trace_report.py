"""Summarize a run-trace JSONL file into human-readable per-phase tables.

Usage:
    python tools/trace_report.py TRACE.jsonl [--validate]
    python tools/trace_report.py --job JOB_DIR [--validate]
    python tools/trace_report.py --fleet DIR_OR_TRACES... [--validate]

``--fleet`` merges ANY set of trace artifacts (directories expand to
their ``fleet.jsonl`` / ``service.jsonl`` / per-job and per-rank
``trace.jsonl`` / ``flight.jsonl`` — ``stateright_tpu.obs.aggregate``)
into ONE wall-anchored timeline and renders per-host / per-job
swimlanes: one row per lane, ~64 time buckets, progress density as
``.``/``:``/``#`` and interventions as letter marks (G row, R etry,
D egrade, H ost_drop, P ause, S pill, E rror, * discovery, ...),
followed by the merged intervention list with fleet-relative
timestamps and the cross-host skew bound (the ``dcn_probe`` round
trip) below which cross-host ordering is not meaningful.

``--job`` accepts a job directory (the service's per-job layout, or any
``tpu_options(artifact_dir=...)`` run) and auto-locates its artifacts:
``trace.jsonl``, the ``flight.jsonl`` postmortem dump, and — for a
service ROOT directory — ``service.jsonl`` plus every job
subdirectory's traces.

Consumes the event stream written by ``tpu_options(trace="...")``
(schema: ``stateright_tpu.obs.EVENT_SCHEMA``) and prints, per engine
found in the trace:

  * the run header (model, wall start, properties, fault injection);
  * a per-event-type table (count, first/last timestamp);
  * a chunk/level timeline in ~12 buckets — unique-states rate, dedup
    hit-rate, table load factor, queue depth — the view that makes a
    pipeline stall or a growth storm visible after the fact;
  * interventions (grow/hgrow/egrow/kovf/compile, the resilience
    layer's retry/watchdog/autosave/failover/degrade and the tiering
    layer's spill/evict events, flight-recorder dumps, and the soak
    harness's live crash/restart/partition injections) with
    timestamps — on a flaky round this table says *where* the tunnel
    dropped, what the engine did about it, and whether an autosave
    landed;
  * a fleet summary line (process/host counts, the DCN round-trip
    probe, ranks joined, hosts dropped by the ladder's host rung) when
    the trace came from a multi-host mesh or the fleet launcher
    (``tools/mesh_launch.py``);
  * a memory-tiering summary line (spills, keys evicted to the host
    tier, the tier population and hot-set size after the last spill)
    when the run hit its HBM budget;
  * a soak summary line (ops, op timeouts, fault-injection counts,
    the history cross-check verdict) when the trace came from
    ``tools/soak.py``;
  * a resilience summary line (retries/watchdogs/failovers/degrades/
    corruptions/quarantines, the blamed device indices, and the mesh
    width a degraded run finished on);
  * an audit summary line (chunks sampled by the silent-corruption
    auditor, frontier rows re-executed, mismatches, and which devices
    lied) when the run enabled ``tpu_options(audit=...)``;
  * discoveries and the final counts.

``--validate`` additionally schema-checks every event and exits
non-zero on the first violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_events(path):
    events = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as exc:
                raise SystemExit(
                    f"{path}:{lineno}: not JSONL ({exc})")
    return events


def _fmt_row(cols, widths):
    return "  ".join(str(c).rjust(w) for c, w in zip(cols, widths))


def _bucketize(rows, n_buckets=12):
    """Group progress rows (dicts with 't') into ~n_buckets spans."""
    if not rows:
        return []
    t0, t1 = rows[0]["t"], rows[-1]["t"]
    span = max(t1 - t0, 1e-9)
    step = span / n_buckets
    buckets = []
    for row in rows:
        idx = min(int((row["t"] - t0) / step), n_buckets - 1)
        if not buckets or buckets[-1][0] != idx:
            buckets.append([idx, []])
        buckets[-1][1].append(row)
    return buckets


def chunk_timeline(rows, out):
    """The stall view: per time bucket, the unique-state rate plus the
    mean dedup hit-rate / load factor / queue depth. A rate collapsing
    while load climbs toward grow_at reads as a growth storm; a flat
    rate with dedup_hit -> 1.0 means the frontier is re-generating
    explored states (raise capacity or rethink the model bounds)."""
    buckets = _bucketize(rows)
    if not buckets:
        return
    widths = (9, 9, 10, 10, 9, 10)
    out.write(_fmt_row(("t_start", "events", "uniq/s", "dedup_hit",
                        "load", "q_size"), widths) + "\n")
    prev_t = 0.0
    prev_uniq = 0
    for _idx, rs in buckets:
        t_end = rs[-1]["t"]
        uniq = rs[-1].get("unique")
        dt = max(t_end - prev_t, 1e-9)
        rate = ("-" if uniq is None
                else f"{(uniq - prev_uniq) / dt:,.0f}")
        dh = [r["dedup_hit"] for r in rs if "dedup_hit" in r]
        ld = [r["load"] for r in rs if "load" in r]
        qs = [r["q_size"] for r in rs if "q_size" in r]
        out.write(_fmt_row((
            f"{rs[0]['t']:.2f}", len(rs), rate,
            f"{sum(dh) / len(dh):.3f}" if dh else "-",
            f"{max(ld):.4f}" if ld else "-",
            max(qs) if qs else "-"), widths) + "\n")
        prev_t, prev_uniq = t_end, uniq if uniq is not None else prev_uniq


def report(events, out=None):
    # late-bind stdout: a default argument would freeze whatever stream
    # was installed at import time (pytest capture, redirections)
    out = sys.stdout if out is None else out
    by_engine = {}
    for ev in events:
        by_engine.setdefault(ev.get("engine", "?"), []).append(ev)
    for engine, evs in by_engine.items():
        out.write(f"=== engine: {engine} ({len(evs)} events, "
                  f"{evs[-1]['t'] - evs[0]['t']:.3f}s) ===\n")
        for ev in evs:
            if ev["ev"] == "run_start":
                out.write(f"model={ev.get('model')} "
                          f"properties={ev.get('properties')}\n")
            elif ev["ev"] == "fault_injection":
                out.write(f"fault injection: max_crashes="
                          f"{ev.get('max_crashes')} "
                          f"actors={ev.get('actors', 'all')}\n")

        # per-event-type table
        kinds = {}
        for ev in evs:
            kinds.setdefault(ev["ev"], []).append(ev["t"])
        widths = (14, 7, 10, 10)
        out.write("\n" + _fmt_row(("event", "count", "first_t",
                                   "last_t"), widths) + "\n")
        for kind in sorted(kinds, key=lambda k: kinds[k][0]):
            ts = kinds[kind]
            out.write(_fmt_row((kind, len(ts), f"{ts[0]:.3f}",
                                f"{ts[-1]:.3f}"), widths) + "\n")

        progress = [e for e in evs
                    if e["ev"] in ("chunk", "level", "progress")]
        if progress:
            out.write("\ntimeline:\n")
            chunk_timeline(progress, out)

        inters = [e for e in evs if e["ev"] in
                  ("grow", "hgrow", "egrow", "kovf", "compile",
                   "retry", "watchdog", "autosave", "failover",
                   "degrade", "promote", "host_promote",
                   "fused_fallback", "fused_unsupported",
                   "recorder_dump",
                   "corruption", "quarantine",
                   "spill", "evict", "pause",
                   "crash", "restart", "partition",
                   "soak_start", "violation", "burnin_preempt",
                   "job_submit", "job_start", "job_pause",
                   "job_resume", "job_done",
                   "job_promote", "job_demote",
                   "bucket_flush", "batch_form", "lane_retire",
                   "mesh_init", "host_join", "host_drop")]
        if inters:
            out.write("\ninterventions:\n")
            for ev in inters:
                detail = {k: v for k, v in ev.items()
                          if k not in ("t", "ev", "engine")}
                out.write(f"  t={ev['t']:9.3f}  {ev['ev']:8} {detail}\n")

        # resilience summary: how the run survived (and on how many
        # chips it finished) — retries/failovers alongside the ladder's
        # degrades, with every chip the faults were blamed on
        resil = [e for e in evs
                 if e["ev"] in ("retry", "failover", "degrade",
                                "promote", "watchdog",
                                "corruption", "quarantine")]
        if resil:
            counts = {}
            for ev in resil:
                counts[ev["ev"]] = counts.get(ev["ev"], 0) + 1
            plural = {"retry": "retries", "watchdog": "watchdogs",
                      "failover": "failovers", "degrade": "degrades",
                      "promote": "promotes",
                      "corruption": "corruptions",
                      "quarantine": "quarantines"}
            parts = [f"{plural[kind]}={counts[kind]}"
                     for kind in ("retry", "watchdog", "failover",
                                  "degrade", "promote",
                                  "corruption", "quarantine")
                     if kind in counts]
            blamed = sorted({ev["device"] for ev in resil
                             if ev.get("device") is not None})
            if blamed:
                parts.append(f"blamed_devices={blamed}")
            # the ladder runs BOTH ways now: the final width is the
            # last rung taken in either direction
            rungs = [e for e in resil
                     if e["ev"] in ("degrade", "promote")]
            if rungs:
                parts.append(
                    f"final_mesh={rungs[-1]['to_shards']}")
            out.write("\nresilience: " + " ".join(parts) + "\n")

        # audit summary: the silent-corruption defense's verdict —
        # chunks sampled, frontier rows re-executed on a second
        # device (or the host oracle), and how many disagreed
        audits = [e for e in evs if e["ev"] == "audit"]
        if audits:
            bad = sum(e.get("mismatches", 0) or 0 for e in audits)
            parts = [f"audits={len(audits)}",
                     f"rows={sum(e.get('rows', 0) or 0 for e in audits)}",
                     f"mismatches={bad}"]
            liars = sorted({e["device"] for e in audits
                            if e.get("mismatches")
                            and e.get("device") is not None})
            if liars:
                parts.append(f"lying_devices={liars}")
            out.write("\naudit: " + " ".join(parts) + "\n")

        # fleet summary (stateright_tpu/cluster + multi-host meshes):
        # the mesh's host/process decomposition, the DCN round-trip
        # probe, which ranks joined, and any hosts the degradation
        # ladder dropped mid-run
        mesh_evs = [e for e in evs if e["ev"] == "mesh_init"]
        joins = [e for e in evs if e["ev"] == "host_join"]
        drops = [e for e in evs if e["ev"] == "host_drop"]
        hpromotes = [e for e in evs if e["ev"] == "host_promote"]
        if mesh_evs or joins or drops or hpromotes:
            parts = []
            if mesh_evs:
                last = mesh_evs[-1]
                parts += [f"procs={last.get('procs')}",
                          f"hosts={last.get('hosts')}",
                          f"shards={last.get('shards')}"]
                if last.get("dcn_exchange_s") is not None:
                    parts.append(
                        f"dcn_exchange_s={last['dcn_exchange_s']}")
            if joins:
                parts.append(
                    f"joined={sorted(e.get('host') for e in joins)}")
            if drops:
                parts.append(
                    "host_drops="
                    f"{sorted((str(e.get('host')) for e in drops))}")
            if hpromotes:
                parts.append(
                    "host_promotes="
                    f"{sorted((str(e.get('host')) for e in hpromotes))}")
            out.write("\nfleet: " + " ".join(parts) + "\n")

        # memory-tiering summary: how the run survived its HBM budget —
        # spills taken, keys evicted to the host tier, and the tier
        # population after the last spill (rediscoveries re-promote)
        spills = [e for e in evs if e["ev"] == "spill"]
        if spills:
            evicts = [e for e in evs if e["ev"] == "evict"]
            parts = [f"spills={len(spills)}",
                     f"evicted_keys={sum(e.get('keys', 0) for e in evicts)}",
                     f"host_tier_keys={spills[-1].get('host_tier_keys')}",
                     f"hot={spills[-1].get('hot')}"]
            reasons = sorted({e.get("reason", "?") for e in spills})
            parts.append(f"reasons={reasons}")
            out.write("\ntiering: " + " ".join(parts) + "\n")

        # soak summary: a chaos soak postmortem reads like a checker
        # postmortem — op throughput, the live faults injected, and
        # whether the recorded history survived the consistency
        # cross-check
        soak_done = [e for e in evs if e["ev"] == "soak_done"]
        if soak_done:
            last = soak_done[-1]
            counts = {}
            for ev in evs:
                if ev["ev"] in ("crash", "restart", "partition"):
                    counts[ev["ev"]] = counts.get(ev["ev"], 0) + 1
            ops_evs = [e for e in evs if e["ev"] == "ops"]
            timeouts = ops_evs[-1].get("op_timeouts", 0) \
                if ops_evs else 0
            plural = {"crash": "crashes", "restart": "restarts",
                      "partition": "partitions"}
            parts = [f"ops={last.get('ops')}",
                     f"op_timeouts={timeouts}"]
            parts += [f"{plural[k]}={v}"
                      for k, v in sorted(counts.items())]
            parts.append(f"history_ok={last.get('history_ok')}")
            # the online cross-check's finds: which tester rejected,
            # and — when the incremental checker flagged it mid-run —
            # at which operation the history went bad
            viols = [e for e in evs if e["ev"] == "violation"]
            if viols:
                parts.append(f"violations={len(viols)}")
                pinned = [e["op_index"] for e in viols
                          if e.get("op_index") is not None]
                if pinned:
                    parts.append(f"violation_op={pinned[0]}")
            out.write("\nsoak: " + " ".join(parts) + "\n")

        # job-service summary (engine="service"): per-job lifecycle —
        # when it was submitted/started, pauses (with reasons:
        # user/preempt/shutdown), resumes, and how it ended
        job_evs = [e for e in evs if e["ev"].startswith("job_")]
        if job_evs:
            per_job = {}
            for ev in job_evs:
                per_job.setdefault(ev.get("job", "?"), []).append(ev)
            done = sum(1 for e in job_evs if e["ev"] == "job_done"
                       and e.get("state") == "done")
            failed = sum(1 for e in job_evs if e["ev"] == "job_done"
                         and e.get("state") == "failed")
            preempts = sum(1 for e in job_evs
                           if e["ev"] == "job_pause"
                           and e.get("reason") == "preempt")
            line = (f"\njobs: submitted="
                    f"{sum(1 for e in job_evs if e['ev'] == 'job_submit')} "
                    f"done={done} failed={failed} "
                    f"preemptions={preempts}")
            # burn-in lane visibility: background soak/fuzz jobs
            # synthesized by the scheduler, and their op-boundary
            # hand-offs to real work
            burn = sum(1 for e in job_evs if e["ev"] == "job_submit"
                       and e.get("burnin"))
            bp = [e for e in evs if e["ev"] == "burnin_preempt"]
            if burn or bp:
                line += (f"  burnin: jobs={burn} "
                         f"preempts={len(bp)}")
            out.write(line + "\n")
            for jid in sorted(per_job):
                parts = []
                for ev in per_job[jid]:
                    kind = ev["ev"][4:]  # strip "job_"
                    extra = ""
                    if ev["ev"] == "job_start" \
                            or ev["ev"] == "job_resume":
                        extra = f"(w={ev.get('width')})"
                    elif ev["ev"] == "job_pause":
                        extra = f"({ev.get('reason')})"
                    elif ev["ev"] == "job_done":
                        extra = f"({ev.get('state')})"
                        # soak/fuzz jobs carry the cross-check verdict
                        if ev.get("history_ok") is False:
                            extra += "(VIOLATION)"
                    parts.append(f"{kind}{extra}@{ev['t']:.2f}")
                out.write(f"  {jid}: " + " -> ".join(parts) + "\n")

        # batch-lane summary (service/batch.py): how many small jobs
        # rode the compile-amortized lane engine, how batches formed,
        # and why lanes retired (done vs solo fallback vs pause)
        batches = [e for e in evs if e["ev"] == "batch_form"]
        retires = [e for e in evs if e["ev"] == "lane_retire"]
        if batches or retires:
            reasons = {}
            for ev in retires:
                r = ev.get("reason", "?")
                reasons[r] = reasons.get(r, 0) + 1
            flushes = [e for e in evs if e["ev"] == "bucket_flush"]
            buckets = sorted({e.get("bucket", "?") for e in batches})
            parts = [f"batches={len(batches)}",
                     f"flushes={len(flushes)}",
                     f"lane_retires={len(retires)}"]
            if reasons:
                parts.append("reasons=" + ",".join(
                    f"{k}:{v}" for k, v in sorted(reasons.items())))
            out.write("\nbatching: " + " ".join(parts) + "\n")
            for b in buckets:
                lanes = [e.get("lanes") for e in batches
                         if e.get("bucket") == b]
                out.write(f"  bucket {b}: lanes={lanes[0]}\n")

        # fused-kernel summary: which path the run took, why a
        # fused='auto' attempt fell back (the classified cause) or
        # never fired (the supports() exclusion), and what the
        # cross-chunk dedup ring killed
        fb = [e for e in evs if e["ev"] == "fused_fallback"]
        if fb:
            causes = sorted({e.get("cause", "?") for e in fb})
            out.write(f"\nfused: fallbacks={len(fb)} "
                      f"causes={causes} "
                      f"(staged path ran; first error: "
                      f"{fb[0].get('error', '?')!r})\n")
        unsup = [e for e in evs if e["ev"] == "fused_unsupported"]
        if unsup:
            out.write(f"\nfused: unsupported — "
                      f"{unsup[0].get('reason', '?')}\n")
        cc_hits = sum(e.get("cc_hits") or 0
                      for e in evs if e["ev"] == "chunk")
        if cc_hits:
            out.write(f"\nfused: cc_dedup_hits={cc_hits} "
                      "(cross-chunk ring kills before the table "
                      "probe/exchange)\n")

        for ev in evs:
            if ev["ev"] == "discovery":
                out.write(f"\ndiscovered {ev.get('property')!r} at "
                          f"t={ev['t']:.3f}\n")
        for ev in evs:
            if ev["ev"] == "done":
                out.write(f"done: gen={ev.get('gen')} "
                          f"unique={ev.get('unique')} "
                          f"discoveries={ev.get('discoveries')}\n")
            elif ev["ev"] == "error":
                out.write(f"ERROR: {ev.get('error')}\n")
        out.write("\n")


def render_fleet(timeline, out=None, width: int = 64):
    """Per-host / per-job swimlanes over one merged fleet timeline."""
    from stateright_tpu.obs.aggregate import INTERVENTIONS
    out = sys.stdout if out is None else out
    events = timeline.events
    if not events:
        out.write("fleet timeline: no events\n")
        return
    lanes = timeline.lanes()
    span = max(timeline.span_s, 1e-9)
    t_min = min(e["fleet_t"] for e in events if e.get("anchored")) \
        if any(e.get("anchored") for e in events) else 0.0
    out.write(
        f"=== fleet timeline: {len(timeline.segments)} streams, "
        f"{len(events)} events, span {span:.3f}s, "
        f"skew_bound={timeline.skew_bound_s * 1e3:.3f}ms ===\n")
    unanchored = sum(1 for e in events if not e.get("anchored"))
    if unanchored:
        out.write(f"(!) {unanchored} events from pre-header streams "
                  "have no wall anchor; placed at relative time\n")
    # per-lane bucket rows: progress density beneath intervention marks
    label_w = max(len(lane) for lane in lanes)
    label_w = min(max(label_w, 4), 36)
    for lane in lanes:
        marks = [" "] * width
        density = [0] * width
        for ev in events:
            if ev["lane_key"] != lane:
                continue
            idx = min(int((ev["fleet_t"] - t_min) / span * width),
                      width - 1)
            kind = ev.get("ev")
            if kind in ("chunk", "level", "progress", "ops",
                        "pool_util"):
                density[idx] += 1
            else:
                mark = INTERVENTIONS.get(kind)
                if mark and marks[idx] == " ":
                    marks[idx] = mark
        row = []
        for i in range(width):
            if marks[i] != " ":
                row.append(marks[i])
            elif density[i] >= 4:
                row.append("#")
            elif density[i] >= 2:
                row.append(":")
            elif density[i] >= 1:
                row.append(".")
            else:
                row.append(" ")
        out.write(f"{lane[:label_w]:<{label_w}} |{''.join(row)}|\n")
    # the merged intervention list, fleet-relative
    inters = [e for e in events
              if e.get("ev") in INTERVENTIONS
              and e["ev"] not in ("compile", "discovery")]
    if inters:
        out.write("\ninterventions (fleet_t):\n")
        for ev in inters:
            detail = {k: v for k, v in ev.items()
                      if k not in ("t", "ev", "engine", "wall",
                                   "fleet_t", "lane_key", "src",
                                   "anchored", "run_id", "host",
                                   "rank")}
            out.write(f"  t={ev['fleet_t']:9.3f}  "
                      f"{ev['ev']:<14} [{ev['lane_key']}] {detail}\n")
    for ev in events:
        if ev.get("ev") == "discovery":
            out.write(f"\ndiscovered {ev.get('property')!r} on "
                      f"[{ev['lane_key']}] at t={ev['fleet_t']:.3f}\n")
    out.write("\n")


def job_traces(directory):
    """Locate a job directory's (or a service root's) trace artifacts
    by the canonical layout (``stateright_tpu.obs.artifact_paths``)."""
    found = []
    for name in ("service.jsonl", "trace.jsonl", "flight.jsonl"):
        path = os.path.join(directory, name)
        if os.path.isfile(path):
            found.append(path)
    # a service ROOT: include every job subdirectory's traces
    try:
        entries = sorted(os.listdir(directory))
    except OSError:
        entries = []
    for entry in entries:
        sub = os.path.join(directory, entry)
        if not os.path.isdir(sub):
            continue
        if not os.path.isfile(os.path.join(sub, "spec.json")):
            continue
        for name in ("trace.jsonl", "flight.jsonl"):
            path = os.path.join(sub, name)
            if os.path.isfile(path):
                found.append(path)
    return found


def main(argv):
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    validate = "--validate" in argv
    paths = [a for a in argv if not a.startswith("--")]
    if "--fleet" in argv:
        from stateright_tpu.obs import aggregate, validate_event
        if not paths:
            print("--fleet requires trace files or artifact "
                  "directories", file=sys.stderr)
            return 2
        sources = []
        for p in paths:
            if os.path.isdir(p):
                located = aggregate.collect_artifacts(p)
                if not located:
                    print(f"{p}: no trace artifacts found",
                          file=sys.stderr)
                    return 2
                sources.extend(located)
            else:
                sources.append(p)
        timeline = aggregate.merge(sources)
        if validate:
            # annotated events are supersets of the originals, and the
            # schema only pins REQUIRED fields — validate them directly
            for i, ev in enumerate(timeline.events):
                try:
                    validate_event(ev)
                except ValueError as exc:
                    print(f"fleet event {i}: {exc}", file=sys.stderr)
                    return 1
            print(f"fleet: {len(timeline.events)} events from "
                  f"{len(timeline.segments)} streams, schema OK",
                  file=sys.stderr)
        render_fleet(timeline)
        return 0
    if "--job" in argv:
        job_dirs = [paths.pop(paths.index(a))
                    for a in list(paths) if os.path.isdir(a)]
        if not job_dirs:
            print("--job requires a job directory", file=sys.stderr)
            return 2
        for d in job_dirs:
            located = job_traces(d)
            if not located:
                print(f"{d}: no trace artifacts found "
                      "(expected trace.jsonl / flight.jsonl / "
                      "service.jsonl)", file=sys.stderr)
                return 2
            print(f"# {d}: {len(located)} artifact(s)",
                  file=sys.stderr)
            paths.extend(located)
    for path in paths:
        events = load_events(path)
        if validate:
            from stateright_tpu.obs import validate_event
            for i, ev in enumerate(events):
                try:
                    validate_event(ev)
                except ValueError as exc:
                    print(f"{path}: event {i}: {exc}", file=sys.stderr)
                    return 1
            print(f"{path}: {len(events)} events, schema OK",
                  file=sys.stderr)
        report(events)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
