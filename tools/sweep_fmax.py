"""Engine tuning sweeps (fmax/kmax/chunk_steps) on the real chip."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bench(mk, warm_arg, runs):
    mk(warm_arg)
    rates = []
    ck = None
    for _ in range(runs):
        t0 = time.perf_counter()
        ck, denom = mk(None)
        rates.append(denom / (time.perf_counter() - t0))
    return rates, ck


def paxos(fmax=None, kmax=None, cap=500_000, runs=3, steps=None):
    from stateright_tpu.examples.paxos_packed import PackedPaxos
    opts = {"capacity": 1 << 21, "race": False}
    for k, v in (("fmax", fmax), ("kmax", kmax), ("chunk_steps", steps)):
        if v:
            opts[k] = v

    def mk(warm):
        ck = (PackedPaxos(3).checker().tpu_options(**opts)
              .target_state_count(warm or cap).spawn_tpu().join())
        return ck, ck.unique_state_count()

    rates, ck = _bench(mk, 50_000, runs)
    print(f"paxos fmax={fmax} kmax={kmax} steps={steps}: "
          f"best={max(rates):,.0f} rates={[f'{r:,.0f}' for r in rates]} "
          f"vmax={ck.profile().get('vmax')}")


def twopc(fmax=None, kmax=None, runs=3):
    from stateright_tpu.models.twopc import TwoPhaseSys
    opts = {"capacity": 1 << 22, "race": False}
    for k, v in (("fmax", fmax), ("kmax", kmax)):
        if v:
            opts[k] = v

    def mk(_warm):
        ck = TwoPhaseSys(7).checker().tpu_options(**opts) \
            .spawn_tpu().join()
        assert ck.unique_state_count() == 296448
        return ck, 296448

    rates, ck = _bench(mk, None, runs)
    print(f"2pc fmax={fmax} kmax={kmax}: best={max(rates):,.0f} "
          f"rates={[f'{r:,.0f}' for r in rates]} "
          f"vmax={ck.profile().get('vmax')}")


def abd(fmax=None, kmax=None, cap=100_000, runs=3):
    from stateright_tpu.examples.abd_packed import PackedAbd
    opts = {"capacity": 1 << 20, "race": False}
    for k, v in (("fmax", fmax), ("kmax", kmax)):
        if v:
            opts[k] = v

    def mk(warm):
        ck = (PackedAbd(2, server_count=3, ordered=True, channel_depth=8)
              .checker().tpu_options(**opts)
              .target_state_count(warm or cap).spawn_tpu().join())
        return ck, ck.unique_state_count()

    rates, ck = _bench(mk, 10_000, runs)
    print(f"abd fmax={fmax} kmax={kmax}: best={max(rates):,.0f} "
          f"rates={[f'{r:,.0f}' for r in rates]} "
          f"vmax={ck.profile().get('vmax')}")


if __name__ == "__main__":
    for arg in sys.argv[1:]:
        eval(arg)
