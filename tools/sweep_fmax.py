import sys, time

def paxos(fmax=None, kmax=None, cap=500_000, runs=2):
    from stateright_tpu.examples.paxos_packed import PackedPaxos
    opts = {"capacity": 1 << 21}
    if fmax: opts["fmax"] = fmax
    if kmax: opts["kmax"] = kmax
    def run(c):
        t0 = time.perf_counter()
        ck = (PackedPaxos(3).checker().tpu_options(**opts)
              .target_state_count(c).spawn_tpu().join())
        return time.perf_counter() - t0, ck
    run(50_000)
    rates = []
    for _ in range(runs):
        dt, ck = run(cap)
        rates.append(ck.unique_state_count() / dt)
    print(f"paxos fmax={fmax} kmax={kmax}: best={max(rates):,.0f} "
          f"rates={[f'{r:,.0f}' for r in rates]} vmax={ck.profile().get('vmax')}")

def twopc(fmax=None, kmax=None, runs=2):
    from stateright_tpu.models.twopc import TwoPhaseSys
    opts = {"capacity": 1 << 22}
    if fmax: opts["fmax"] = fmax
    if kmax: opts["kmax"] = kmax
    def run():
        t0 = time.perf_counter()
        ck = (TwoPhaseSys(7).checker().tpu_options(**opts)
              .spawn_tpu().join())
        return time.perf_counter() - t0, ck
    run()
    rates = []
    for _ in range(runs):
        dt, ck = run()
        assert ck.unique_state_count() == 296448
        rates.append(296448 / dt)
    print(f"2pc fmax={fmax} kmax={kmax}: best={max(rates):,.0f} "
          f"rates={[f'{r:,.0f}' for r in rates]} vmax={ck.profile().get('vmax')}")

if __name__ == "__main__":
    for arg in sys.argv[1:]:
        eval(arg)
