"""Bench-trend gate: aggregate the committed BENCH_*.json artifacts.

Usage:
    python tools/bench_history.py [DIR | FILES...]
        [--json PATH|-] [--markdown PATH|-]
        [--threshold 0.25] [--check] [--allow kind:round[,kind:round]]

The perf trajectory lives in per-round artifacts (``BENCH_r01.json``,
``BENCH_r02.json``, ...) that nothing aggregated: BENCH_r05 shipped
*empty* (rc=1, ``parsed: null`` — the backend died at init) and only a
human reviewer noticed. This tool is the machine that notices:

* **trend table** — one row per workload (names normalized across
  cap changes: ``tpu paxos3 capped 500k`` and ``... capped 40000`` are
  the same trend line), one column per round, each cell the best rate
  with its tags (``fused``/``staged``, ``degraded``,
  ``init_fallback``, ``multihost``) — so a round whose number was
  measured on a degraded mesh, a CPU fallback, or a DCN-spanning
  fleet mesh can never silently ride the trajectory as a
  single-host device number;
* **flags** — machine-readable problems: empty artifacts (rc != 0,
  ``parsed: null``), partial contract lines, per-workload error rows,
  workloads that vanished between rounds, and regressions (best rate
  dropping more than ``--threshold``, default 25%, round over round on
  comparable tags);
* **outputs** — a markdown report (default: stdout) and a JSON
  document (``--json -`` for stdout, ``--json PATH`` to write); with
  ``--check`` the exit code is 1 when any flag fired — the CI gate.
  ``--allow kind:round`` (repeatable, comma-separable) acknowledges a
  KNOWN-bad artifact (e.g. ``--allow empty_artifact:r05`` for the
  round-5 rc=1 hole) so the gate stays red only for NEW problems; the
  allowed flags are still reported, marked ``(allowed)``.

The contract line itself rides the table as workload ``<contract>``.
This output is the single source of truth for trajectory numbers —
README and NOTES quote it rather than hand-copied rates.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

#: tokens stripped from workload names so trend lines survive cap
#: changes between rounds ("capped 500k" vs "capped 40000"); the size
#: token FOLLOWING one of these is stripped too ("capped 1M-gen") —
#: but model-size tokens like "2pc7" stay, they ARE the workload
_CAP_TOKENS = {"capped", "full"}

CONTRACT = "<contract>"


def normalize_workload(name: str) -> str:
    """Collapse run-size tokens out of a workload name."""
    out = []
    skip_next = False
    for tok in name.split():
        if skip_next:
            skip_next = False
            continue
        if tok in _CAP_TOKENS:
            skip_next = True
            continue
        out.append(tok)
    return " ".join(out) or name


def _round_key(path: str) -> str:
    m = re.search(r"BENCH_(r\d+)", os.path.basename(path))
    return m.group(1) if m else os.path.basename(path)


def parse_round(path: str) -> Dict[str, Any]:
    """One artifact -> {round, rc, contract, workloads, errors}.

    Workload rows are the JSON lines bench.py printed to stderr
    (captured in the artifact's ``tail``); rounds before the
    structured rows (r01-r03) simply contribute no per-workload data.
    """
    with open(path) as f:
        art = json.load(f)
    rnd: Dict[str, Any] = {
        "round": _round_key(path),
        "path": os.path.basename(path),
        "rc": art.get("rc"),
        "contract": art.get("parsed"),
        "workloads": {},
        "errors": [],
    }
    for line in (art.get("tail") or "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        name = row.get("workload")
        if not name:
            continue
        if "error" in row:
            rnd["errors"].append({"workload": name,
                                  "error": row["error"]})
            continue
        if "skipped" in row:
            continue
        if "best" not in row and "jobs_per_min" in row:
            # a --job-storm mode row: jobs/min IS the trend number
            # (one line per mode, so a batched regression can never
            # hide behind an unbatched improvement)
            rnd["workloads"][normalize_workload(name)] = {
                "name": name,
                "best": row["jobs_per_min"],
                "median": None,
                "unit": "jobs/min",
                "uniq": None,
                "gen_per_uniq": None,
                "tags": sorted(t for t, on in (
                    ("storm", True),
                    ("partial", bool(row.get("failed"))),
                ) if on),
            }
            continue
        if "best" not in row:
            continue
        metrics = row.get("metrics") or {}
        rnd["workloads"][normalize_workload(name)] = {
            "name": name,
            "best": row.get("best"),
            "median": row.get("median"),
            "unit": row.get("unit"),
            "uniq": row.get("uniq"),
            "gen_per_uniq": row.get("gen_per_uniq"),
            # span attribution (PR 18): the top stall buckets and the
            # pipeline-bubble fraction bench.py embeds per workload —
            # optional (pre-span rounds simply lack them), trended as
            # the dominant-stall column
            "stalls": metrics.get("stalls"),
            "bubble_frac": metrics.get("bubble_frac"),
            # duplicate-expansion factor AFTER the cross-chunk dedup
            # ring's in-register kills (PR 13) — the g/u vs g/u_cc gap
            # is the cache's measured bite, tracked as its own trend
            "gen_per_uniq_cc": row.get("gen_per_uniq_cc"),
            "tags": sorted(
                t for t, on in (
                    ("fused", row.get("fused")),
                    ("staged", row.get("fused") is False),
                    ("degraded", bool(metrics.get("degrades"))),
                    ("retried", bool(metrics.get("retries"))),
                    ("spilled", bool(row.get("spilled"))
                     or bool(metrics.get("spills"))),
                ) if on),
        }
    contract = rnd["contract"]
    if isinstance(contract, dict) and contract.get("value") is not None:
        tags = sorted(
            t for t, on in (
                ("partial", bool(contract.get("partial"))),
                ("degraded", bool(contract.get("degraded"))),
                ("spilled", bool(contract.get("spilled"))),
                ("init_fallback", bool(contract.get("init_fallback"))),
                ("cpu", contract.get("backend") == "cpu"),
                # a --service-smoke round: the value is aggregate
                # job-service throughput, not a device engine rate
                ("service", bool(contract.get("service"))),
                # a --job-storm round: the value is batched jobs/min
                # through the lane engine (jobs_per_min rides the
                # per-mode rows as their own trend lines)
                ("storm", bool(contract.get("storm"))),
                # a --multihost-smoke round: the value is uniq/s of a
                # multi-process fleet mesh spanning DCN — not
                # comparable to single-host device rates
                ("multihost", bool(contract.get("hosts"))),
                # a --burnin-smoke round: the value is burn-in lane
                # jobs/min with a real checking job preempting through
                # — a fleet-behavior number, not an engine rate
                ("burnin", bool(contract.get("burnin"))),
                # a --flex-smoke round: a job storm under a rolling
                # host join/leave with the elastic flex controller on
                # — promote/demote behavior, not an engine rate
                ("flex", bool(contract.get("flex"))),
                # an --audit-smoke round: a lying chip caught by the
                # chunk auditor and replayed to oracle parity — a
                # defense-behavior number, not an engine rate
                ("audit", bool(contract.get("audit"))),
            ) if on)
        rnd["workloads"][CONTRACT] = {
            "name": contract.get("metric", "contract"),
            "best": contract["value"],
            "median": None,
            "unit": contract.get("unit"),
            "uniq": None,
            "gen_per_uniq": None,
            "tags": tags,
        }
    return rnd


def compute_flags(rounds: List[Dict[str, Any]],
                  threshold: float) -> List[Dict[str, Any]]:
    flags: List[Dict[str, Any]] = []
    for rnd in rounds:
        if rnd["rc"] not in (0, None) or rnd["contract"] is None:
            flags.append({
                "kind": "empty_artifact", "round": rnd["round"],
                "detail": f"rc={rnd['rc']}, "
                          f"parsed={'null' if rnd['contract'] is None else 'ok'}"
                          " — no trajectory numbers landed"})
            continue
        c = rnd["contract"]
        if c.get("partial"):
            flags.append({"kind": "partial", "round": rnd["round"],
                          "detail": f"failed={c.get('failed')}"})
        if c.get("init_fallback"):
            flags.append({
                "kind": "init_fallback", "round": rnd["round"],
                "detail": f"backend init failed "
                          f"(cause={c.get('init_cause')}); round ran "
                          "on the CPU fallback — not comparable to "
                          "device rounds"})
        if c.get("degraded"):
            flags.append({
                "kind": "degraded", "round": rnd["round"],
                "detail": f"primary metric finished on "
                          f"{c.get('final_shards')} shard(s)"})
        if c.get("spilled"):
            flags.append({
                "kind": "spilled", "round": rnd["round"],
                "detail": "primary metric hit its HBM budget and "
                          f"finished via host-tier spills "
                          f"({c.get('host_tier_keys')} keys host-"
                          "resident) — not comparable to all-HBM "
                          "rounds"})
        for err in rnd["errors"]:
            flags.append({"kind": "workload_error",
                          "round": rnd["round"],
                          "workload": err["workload"],
                          "detail": err["error"][:200]})
    # span-attribution coverage (PR 18): rounds BEFORE the first
    # attribution-carrying round predate the span profiler — flagged
    # informationally (never fatal, so the committed pre-span
    # artifacts keep the gate green). A LATER round with workload rows
    # but no attribution anywhere regressed the instrument: fatal.
    attr_idx = [i for i, r in enumerate(rounds)
                if _has_attribution(r)]
    if attr_idx:
        first = attr_idx[0]
        for i, rnd in enumerate(rounds):
            if _has_attribution(rnd) or not rnd["workloads"]:
                continue
            if i < first:
                flags.append({
                    "kind": "pre_span", "round": rnd["round"],
                    "info": True,
                    "detail": "round predates the span profiler — no "
                              "attribution fields (informational, "
                              "not fatal)"})
            else:
                flags.append({
                    "kind": "missing_attribution",
                    "round": rnd["round"],
                    "detail": "no workload row carries span "
                              "attribution (stalls/bubble_frac) in a "
                              "round AFTER the profiler landed in "
                              f"{rounds[first]['round']}"})
    # regressions / disappearances: compare each data round against the
    # PREVIOUS round that carried per-workload rows
    data_rounds = [r for r in rounds if r["workloads"]]
    for prev, cur in zip(data_rounds, data_rounds[1:]):
        comparable = (
            "init_fallback" not in _round_tags(prev)
            and "init_fallback" not in _round_tags(cur)
            and _round_backend(prev) == _round_backend(cur))
        for wname, pw in prev["workloads"].items():
            cw = cur["workloads"].get(wname)
            if cw is None:
                if not comparable:
                    # a backend switch legitimately changes the matrix
                    # (a CPU-fallback round skips the device-budget
                    # context workloads) — a "missing" flag there is
                    # noise, same reasoning as the regression gate
                    continue
                flags.append({
                    "kind": "missing_workload", "round": cur["round"],
                    "workload": wname,
                    "detail": f"present in {prev['round']}, absent in "
                              f"{cur['round']}"})
                continue
            if not comparable or pw["unit"] != cw["unit"]:
                continue
            if pw["unit"] == "s":  # latency: higher is worse
                if pw["best"] and cw["best"] > pw["best"] * (
                        1 + threshold):
                    flags.append(_regression(cur, wname, pw, cw,
                                             cw["best"] / pw["best"] - 1,
                                             prev))
            elif pw["best"] and cw["best"] < pw["best"] * (1 - threshold):
                flags.append(_regression(cur, wname, pw, cw,
                                         1 - cw["best"] / pw["best"],
                                         prev))
    return flags


def _has_attribution(rnd) -> bool:
    """True when any workload row of the round carries the span
    profiler's fields (``stalls``/``bubble_frac``)."""
    return any(w.get("stalls") or w.get("bubble_frac") is not None
               for w in rnd["workloads"].values())


def _round_tags(rnd) -> set:
    c = rnd.get("contract") or {}
    return {t for t, on in (
        ("init_fallback", c.get("init_fallback")),) if on}


def _round_backend(rnd) -> Optional[str]:
    c = rnd.get("contract") or {}
    return c.get("backend")


def _regression(cur, wname, pw, cw, drop, prev) -> Dict[str, Any]:
    return {"kind": "regression", "round": cur["round"],
            "workload": wname,
            "detail": f"{pw['best']} -> {cw['best']} {cw['unit']} "
                      f"({drop:.0%} worse than {prev['round']}; "
                      f"tags {pw['tags']} -> {cw['tags']})",
            "drop": round(drop, 4)}


def build_report(paths: List[str],
                 threshold: float = 0.25) -> Dict[str, Any]:
    rounds = [parse_round(p) for p in sorted(paths, key=_round_key)]
    flags = compute_flags(rounds, threshold)
    workloads = sorted({w for r in rounds for w in r["workloads"]})
    trend = {
        w: [{"round": r["round"], **r["workloads"][w]}
            for r in rounds if w in r["workloads"]]
        for w in workloads}
    return {"rounds": rounds, "trend": trend, "flags": flags,
            "threshold": threshold}


def render_markdown(report: Dict[str, Any], out) -> None:
    rounds = report["rounds"]
    out.write("# Bench trend (" + ", ".join(
        r["round"] for r in rounds) + ")\n\n")
    names = sorted(report["trend"])
    if names:
        heads = ["workload"] + [r["round"] for r in rounds]
        out.write("| " + " | ".join(heads) + " |\n")
        out.write("|" + "---|" * len(heads) + "\n")
        for w in names:
            cells = [w]
            by_round = {e["round"]: e for e in report["trend"][w]}
            for r in rounds:
                e = by_round.get(r["round"])
                if e is None:
                    cells.append("—")
                    continue
                cell = f"{e['best']:,} {e['unit']}" \
                    if isinstance(e["best"], (int, float)) else "?"
                if e.get("gen_per_uniq"):
                    cell += f", g/u={e['gen_per_uniq']}"
                if e.get("gen_per_uniq_cc"):
                    cell += f", g/u_cc={e['gen_per_uniq_cc']}"
                if e.get("stalls"):
                    # the dominant-stall trend: the bucket the next
                    # perf PR should target, round over round
                    cell += f", stall={e['stalls'][0][0]}"
                if e.get("bubble_frac") is not None:
                    cell += f", bubble={e['bubble_frac']}"
                if e["tags"]:
                    cell += " [" + ",".join(e["tags"]) + "]"
                cells.append(cell)
            out.write("| " + " | ".join(cells) + " |\n")
        out.write("\n")
    else:
        out.write("(no per-workload rows in any round)\n\n")
    out.write("## Flags\n\n")
    if not report["flags"]:
        out.write("none — every round landed numbers and no workload "
                  "regressed past the threshold\n")
    for f in report["flags"]:
        where = f.get("workload", "")
        out.write(f"* **{f['kind']}** {f['round']}"
                  + (f" `{where}`" if where else "")
                  + f": {f['detail']}"
                  + (" (allowed)" if f.get("allowed") else "")
                  + (" (informational)" if f.get("info") else "")
                  + "\n")


def allowed(flag: Dict[str, Any], allow: List[str]) -> bool:
    """``kind:round`` acknowledgement match for one flag."""
    return f"{flag.get('kind')}:{flag.get('round')}" in allow


def main(argv) -> int:
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    threshold = 0.25
    if "--threshold" in argv:
        threshold = float(argv[argv.index("--threshold") + 1])
    json_to = (argv[argv.index("--json") + 1]
               if "--json" in argv else None)
    md_to = (argv[argv.index("--markdown") + 1]
             if "--markdown" in argv else None)
    allow: List[str] = []
    for i, a in enumerate(argv):
        if a == "--allow":
            allow.extend(argv[i + 1].split(","))
    consumed = set(allow) | ({",".join(allow)} if allow else set())
    positional = [a for a in argv if not a.startswith("--")
                  and a not in (str(threshold), json_to, md_to)
                  and a not in consumed]
    if not positional:
        positional = ["."]
    paths: List[str] = []
    for p in positional:
        if os.path.isdir(p):
            paths.extend(glob.glob(os.path.join(p, "BENCH_*.json")))
        else:
            paths.append(p)
    if not paths:
        print("bench_history.py: no BENCH_*.json artifacts found",
              file=sys.stderr)
        return 2
    report = build_report(paths, threshold)
    for f in report["flags"]:
        if allowed(f, allow):
            f["allowed"] = True
    if json_to == "-":
        json.dump(report, sys.stdout, indent=1, default=str)
        sys.stdout.write("\n")
    elif json_to:
        with open(json_to, "w") as f:
            json.dump(report, f, indent=1, default=str)
    if md_to and md_to != "-":
        with open(md_to, "w") as f:
            render_markdown(report, f)
    elif json_to is None or md_to == "-":
        render_markdown(report, sys.stdout)
    if "--check" in argv:
        # informational flags (pre-span rounds) never fail the gate
        hard = [f for f in report["flags"]
                if not allowed(f, allow) and not f.get("info")]
        if hard:
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
