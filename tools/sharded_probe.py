"""Sharded-engine measurements (VERDICT r3 #4)."""
import time
import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run_plain(runs=3):
    from stateright_tpu.models.twopc import TwoPhaseSys
    def mk():
        t0 = time.perf_counter()
        ck = (TwoPhaseSys(7).checker()
              .tpu_options(capacity=1 << 22, race=False)
              .spawn_tpu().join())
        return time.perf_counter() - t0, ck.unique_state_count()
    mk()
    rates = []
    for _ in range(runs):
        dt, uq = mk()
        assert uq == 296448
        rates.append(uq / dt)
    print(f"plain device 2pc7: best={max(rates):,.0f} "
          f"samples={[f'{r:,.0f}' for r in rates]}")
    return max(rates)


def run_sharded(d=1, runs=3, n=7, expect=296448):
    import jax
    from jax.sharding import Mesh
    from stateright_tpu.models.twopc import TwoPhaseSys
    devices = jax.devices()
    if len(devices) < d:
        print(f"SKIP d={d}: only {len(devices)} devices")
        return None
    mesh = Mesh(np.array(devices[:d]), ("shards",))
    def mk():
        t0 = time.perf_counter()
        ck = (TwoPhaseSys(n).checker()
              .tpu_options(mesh=mesh, capacity=1 << 22)
              .spawn_tpu().join())
        return time.perf_counter() - t0, ck.unique_state_count()
    mk()
    rates = []
    for _ in range(runs):
        dt, uq = mk()
        assert uq == expect, uq
        rates.append(uq / dt)
    print(f"sharded D={d} 2pc{n}: best={max(rates):,.0f} "
          f"samples={[f'{r:,.0f}' for r in rates]}")
    return max(rates)


if __name__ == "__main__":
    which = sys.argv[1]
    if which == "cpu":
        # sitecustomize force-registers the axon plugin; override BEFORE
        # backend init (see tests/conftest.py)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    if which == "tpu":
        p = run_plain()
        s = run_sharded(1)
        if s:
            print(f"D=1 shard_map overhead: {100 * (1 - s / p):.1f}%")
    elif which == "cpu":
        for d in (1, 2, 4, 8):
            run_sharded(d)
