"""Quick A/B probe for engine perf work: paxos-capped + 2pc-full rates,
best-of-N. Not part of the driver contract (bench.py is)."""
import sys
import time


def paxos(n_runs=3, cap=500_000):
    from stateright_tpu.examples.paxos_packed import PackedPaxos

    def run(c):
        t0 = time.perf_counter()
        ck = (PackedPaxos(3).checker()
              .tpu_options(capacity=1 << 21)
              .target_state_count(c)
              .spawn_tpu().join())
        return time.perf_counter() - t0, ck

    run(50_000)  # warm
    rates = []
    for _ in range(n_runs):
        dt, ck = run(cap)
        rates.append(ck.unique_state_count() / dt)
    print(f"paxos3 capped: uniq={ck.unique_state_count()} "
          f"rates={[f'{r:,.0f}' for r in rates]} best={max(rates):,.0f}")
    return max(rates)


def twopc(n_runs=3):
    from stateright_tpu.models.twopc import TwoPhaseSys

    def run():
        t0 = time.perf_counter()
        ck = (TwoPhaseSys(7).checker()
              .tpu_options(capacity=1 << 22)
              .spawn_tpu().join())
        return time.perf_counter() - t0, ck.unique_state_count()

    run()
    rates = []
    for _ in range(n_runs):
        dt, uq = run()
        assert uq == 296448, uq
        rates.append(uq / dt)
    print(f"2pc n=7 full: uniq={uq} "
          f"rates={[f'{r:,.0f}' for r in rates]} best={max(rates):,.0f}")
    return max(rates)


def abd(n_runs=3, cap=100_000):
    from stateright_tpu.examples.abd_packed import PackedAbd

    def run(c):
        t0 = time.perf_counter()
        ck = (PackedAbd(2, server_count=3, ordered=True, channel_depth=8)
              .checker()
              .tpu_options(capacity=1 << 20)
              .target_state_count(c)
              .spawn_tpu().join())
        return time.perf_counter() - t0, ck

    run(5_000)
    rates = []
    for _ in range(n_runs):
        dt, ck = run(cap)
        rates.append(ck.unique_state_count() / dt)
    print(f"abd2 ordered capped: uniq={ck.unique_state_count()} "
          f"rates={[f'{r:,.0f}' for r in rates]} best={max(rates):,.0f}")
    return max(rates)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "paxos"):
        paxos()
    if which in ("all", "2pc"):
        twopc()
    if which in ("all", "abd"):
        abd()
