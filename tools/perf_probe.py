"""Quick A/B probe for engine perf work: paxos-capped + 2pc-full rates,
best-of-N, each run followed by a run-trace summary (chunk count, mean
dedup hit-rate, peak table load, interventions) — the trace, not ad-hoc
prints, is the explanation channel. Not part of the driver contract
(bench.py is)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _trace_line(events, prof):
    """One summary line per run — a thin shim over the span consumer
    (tools/stall_report.py): the overlap-aware attribution replaces
    the old hand-parsed chunk/stall ratios, which double-counted under
    the pipeline."""
    import stall_report
    attr, imb = stall_report.attribution_from_events(events)
    chunks = sum(1 for e in events if e.get("ev") == "chunk")
    return (f"  trace: chunks={chunks} "
            + stall_report.summary_line(attr, imb))


def _probe(name, mk, n_runs, warm):
    warm()
    rates = []
    events = []
    ck = None
    for _ in range(n_runs):
        events.clear()
        t0 = time.perf_counter()
        ck = mk(events)
        rates.append(ck.unique_state_count()
                     / (time.perf_counter() - t0))
    print(f"{name}: uniq={ck.unique_state_count()} "
          f"rates={[f'{r:,.0f}' for r in rates]} best={max(rates):,.0f}")
    print(_trace_line(events, ck.profile()))
    return max(rates)


def paxos(n_runs=3, cap=500_000):
    from stateright_tpu.examples.paxos_packed import PackedPaxos

    def mk(events, c=cap):
        return (PackedPaxos(3).checker()
                .tpu_options(capacity=1 << 21, race=False, trace=events)
                .target_state_count(c)
                .spawn_tpu().join())

    return _probe("paxos3 capped", mk, n_runs,
                  warm=lambda: mk([], 50_000))


def twopc(n_runs=3):
    from stateright_tpu.models.twopc import TwoPhaseSys

    def mk(events):
        ck = (TwoPhaseSys(7).checker()
              .tpu_options(capacity=1 << 22, race=False, trace=events)
              .spawn_tpu().join())
        assert ck.unique_state_count() == 296448, ck.unique_state_count()
        return ck

    return _probe("2pc n=7 full", mk, n_runs, warm=lambda: mk([]))


def abd(n_runs=3, cap=100_000):
    from stateright_tpu.examples.abd_packed import PackedAbd

    def mk(events, c=cap):
        return (PackedAbd(2, server_count=3, ordered=True,
                          channel_depth=8)
                .checker()
                .tpu_options(capacity=1 << 20, race=False, trace=events)
                .target_state_count(c)
                .spawn_tpu().join())

    return _probe("abd2 ordered capped", mk, n_runs,
                  warm=lambda: mk([], 5_000))


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "paxos"):
        paxos()
    if which in ("all", "2pc"):
        twopc()
    if which in ("all", "abd"):
        abd()
