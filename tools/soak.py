"""Chaos soak CLI — thin shim over :mod:`stateright_tpu.soak`.

The driver moved INTO the package in PR 15 so the job service can run
soak/fuzz configurations as first-class scheduled jobs
(``service/scheduler.py`` ``kind: soak|fuzz`` specs over
``SOAK_REGISTRY``); this file keeps the historical CLI entry point and
re-exports the full driver surface for existing consumers
(``tests/test_soak.py``, ``tests/test_fuzz_differential.py``,
``bench.py --soak-smoke``).

Usage:
    python tools/soak.py [--protocol write_once|abd] [--ops N]
                         [--clients N] [--seed N] [--volatile]
                         [--loss P] [--duplicate P] [--delay P]
                         [--crashes N] [--partitions N] [--trace PATH]
                         [--artifact-dir DIR] [--posthoc]

See ``stateright_tpu/soak.py`` for the full documentation (online
linearizability checking, the seed-corpus dedup key, the soak-config
registry, obs integration).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from stateright_tpu.soak import (  # noqa: E402,F401
    _PROTOCOLS, SOAK_REGISTRY, DurableAbdActor, DurableWOServer,
    SoakConfig, VolatileWOServer, artifact_filename, build_soak_config,
    check_artifact, file_violation, fuzz_config, known_soak_configs,
    main, register_soak_config, run_soak, spec_for, tester_for,
    volatile_demo_config)

if __name__ == "__main__":
    raise SystemExit(main())
