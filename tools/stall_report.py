"""Overlap-aware stall attribution: where did the wall time actually go?

Usage:
    python tools/stall_report.py TRACE.jsonl...
    python tools/stall_report.py --fleet DIR_OR_TRACES...

Consumes the ``span`` events emitted by the engines' dispatch/process
loops (``stateright_tpu/obs/spans.py``) and renders the ranked stall
table from the overlap-aware critical-path sweep: wall time split into
exclusively-attributed buckets that SUM TO WALL — ``device``/``xfer``/
``exchange`` segments where only the device pipeline was busy,
``overlap`` where host work hid under an in-flight chunk (free, the
pipeline doing its job), ``host:<phase>`` where the host blocked an
idle device (the pipeline bubble), and ``idle`` dead air. The flat
phase timers (``dispatch``/``sync_stall``/``host_overlap``) double-
count under the double-buffered pipeline; this report is the
actionable replacement — the biggest non-overlap row is the next perf
target.

``--fleet`` merges any set of trace artifacts (directories expand via
``stateright_tpu.obs.aggregate.collect_artifacts``) onto one
wall-anchored timeline and reports per lane (per job / per rank) with
a merged summary; sharded runs get a per-shard imbalance column from
their ``chunk`` events' ``shard_new`` vectors.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _spans_mod():
    from stateright_tpu.obs import spans
    return spans


def attribution_from_events(events, wall=False):
    """``(attribution, imbalance)`` for one event stream — the shared
    consumer entry point (``perf_probe``/``prof_chunk`` shims and the
    tests call this instead of hand-parsing the trace)."""
    spans = _spans_mod()
    attr = spans.analyze(spans.spans_from_events(events, wall=wall))
    return attr, spans.shard_imbalance(events)


def summary_line(attr, imbalance=None, top=3):
    """One compact stall line (the live-console / perf-probe form):
    top buckets by share plus the bubble fraction."""
    spans = _spans_mod()
    if not attr or not attr.get("buckets"):
        return "stall: no spans"
    bits = [f"{name}={share:.0%}"
            for name, _secs, share in spans.ranked(attr)[:top]]
    bits.append(f"bubble={attr['bubble_frac']:.0%}")
    if imbalance is not None:
        bits.append(f"imbalance={imbalance['imbalance']:.2f}")
    return "stall: " + " ".join(bits)


def render(attr, imbalance=None, title=None, out=None):
    """The ranked stall table for one attribution (rows sum to wall)."""
    out = sys.stdout if out is None else out
    spans = _spans_mod()
    if title:
        print(f"# {title}", file=out)
    if not attr or not attr.get("spans"):
        print("  no span events (pre-span trace, or tracing was off)",
              file=out)
        return
    wall = attr["wall_s"]
    print(f"  wall {wall:.3f}s across {attr['spans']} spans "
          f"(span extent [{attr['t0']:.3f}, {attr['t1']:.3f}])",
          file=out)
    rows = spans.ranked(attr)
    name_w = max([len("bucket")] + [len(n) for n, _s, _f in rows])
    print(f"  {'bucket':<{name_w}}  {'seconds':>10}  {'share':>6}",
          file=out)
    total = 0.0
    for name, secs, share in rows:
        total += secs
        print(f"  {name:<{name_w}}  {secs:>10.4f}  {share:>6.1%}",
              file=out)
    print(f"  {'-' * name_w}  {'-' * 10}  {'-' * 6}", file=out)
    share = (total / wall) if wall > 0 else 0.0
    print(f"  {'sum':<{name_w}}  {total:>10.4f}  {share:>6.1%}",
          file=out)
    print(f"  bubble_frac={attr['bubble_frac']:.3f} "
          f"(host-blocking + idle share) "
          f"idle_s={attr['idle_s']:.4f} "
          f"overlap_s={attr['overlap_s']:.4f}", file=out)
    if imbalance is not None:
        print(f"  shard imbalance: max/mean="
              f"{imbalance['imbalance']:.2f} "
              f"per-shard new={imbalance['per_shard_new']}", file=out)


def render_fleet(timeline, out=None):
    """Per-lane stall tables + the merged fleet summary row set."""
    out = sys.stdout if out is None else out
    spans = _spans_mod()
    by_lane = {}
    for ev in timeline.events:
        by_lane.setdefault(ev.get("lane_key", "?"), []).append(ev)
    all_spans = []
    summary = []
    for lane in timeline.lanes():
        events = by_lane.get(lane, [])
        lane_spans = spans.spans_from_events(events, wall=True)
        all_spans.extend(lane_spans)
        attr = spans.analyze(lane_spans)
        imb = spans.shard_imbalance(events)
        if not attr["spans"]:
            continue
        ranked = spans.ranked(attr)
        top = f"{ranked[0][0]}={ranked[0][2]:.0%}" if ranked else "-"
        summary.append((lane, attr, imb, top))
        render(attr, imb, title=f"lane {lane}", out=out)
    if not summary:
        print("  no span events on the fleet timeline", file=out)
        return
    print("# fleet summary (per lane)", file=out)
    lane_w = max([len("lane")] + [len(s[0]) for s in summary])
    print(f"  {'lane':<{lane_w}}  {'wall_s':>8}  {'top stall':<18}"
          f"  {'bubble':>6}  {'imbal':>5}", file=out)
    for lane, attr, imb, top in summary:
        imb_s = f"{imb['imbalance']:.2f}" if imb is not None else "-"
        print(f"  {lane:<{lane_w}}  {attr['wall_s']:>8.3f}  "
              f"{top:<18}  {attr['bubble_frac']:>6.1%}  {imb_s:>5}",
              file=out)
    merged = spans.analyze(all_spans)
    render(merged, title="merged (wall-anchored, all lanes)", out=out)


def main(argv):
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        print("stall_report: no trace files given", file=sys.stderr)
        return 2
    if "--fleet" in argv:
        from stateright_tpu.obs import aggregate
        sources = []
        for p in paths:
            if os.path.isdir(p):
                located = aggregate.collect_artifacts(p)
                if not located:
                    print(f"{p}: no trace artifacts found",
                          file=sys.stderr)
                    return 2
                sources.extend(located)
            else:
                sources.append(p)
        render_fleet(aggregate.merge(sources))
        return 0
    from trace_report import load_events
    for path in paths:
        if not os.path.isfile(path):
            print(f"{path}: not a file", file=sys.stderr)
            return 2
        events = load_events(path)
        attr, imb = attribution_from_events(events)
        render(attr, imb, title=f"stall attribution: {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
