"""Microbenchmark: staged expand→hash→dedup→probe vs the fused kernel.

Usage:
    python tools/kernel_bench.py [--model 2pc7|2pc4|paxos3] [--fmax N]
                                 [--iters N] [--capacity 2^k] [--out F]

Times ONE device iteration's dedup pipeline both ways, on a synthetic
frontier drawn from the model's real reachable states (BFS prefix):

  * **staged**, per stage — ``expand`` (``ops.expand.expand_frontier``,
    child fingerprints deferred), ``hash`` (``fp64_device`` over the
    compacted raw-valid lanes), ``pre_dedup`` (scatter-min claim arena),
    ``probe`` (``ops.hashtable.table_insert``) — each stage jitted
    standalone so the per-stage costs are visible, plus the composed
    staged pipeline in one jit (what the engines actually run);
  * **fused** (``ops.fused``): the one-kernel
    expand→fingerprint→pre-dedup→probe path.

Emits ONE JSON line on stdout: per-stage milliseconds (median of
``--iters`` timed reps after a compile warm-up), the composed
staged-vs-fused ratio, and the workload's duplicate-lane fraction (the
quantity the fusion attacks). On non-TPU backends the fused path runs
through the Pallas **interpreter** — correctness-representative, NOT
perf-representative; the line carries ``"interpret": true`` so nobody
reads a CPU ratio as a TPU result.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _median(xs):
    s = sorted(xs)
    return s[len(s) // 2]


def _make_model(name: str):
    if name.startswith("2pc"):
        from stateright_tpu.models.twopc import TwoPhaseSys
        return TwoPhaseSys(int(name[3:]))
    if name.startswith("paxos"):
        from stateright_tpu.examples.paxos_packed import PackedPaxos
        return PackedPaxos(int(name[5:]))
    raise SystemExit(f"unknown --model {name!r} (want 2pcN or paxosN)")


def _frontier(model, fmax: int):
    """A real frontier slab: BFS from the inits until fmax rows exist
    (duplicate structure matters — a random frontier would understate
    the dedup stages)."""
    import numpy as np

    seen = set()
    rows = []
    queue = [s for s in model.init_states() if model.within_boundary(s)]
    while queue and len(rows) < fmax:
        state = queue.pop(0)
        fp = model.fingerprint(state)
        if fp in seen:
            continue
        seen.add(fp)
        rows.append(np.asarray(model.encode(state), np.uint32))
        for _a, nxt in model.next_steps(state):
            queue.append(nxt)
    while len(rows) < fmax:  # tiny models: tile the reached set
        rows.append(rows[len(rows) % max(len(seen), 1)])
    return np.stack(rows[:fmax])


def _timed(fn, args, iters: int):
    import jax

    out = fn(*args)  # compile + warm
    jax.block_until_ready(out)
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        samples.append((time.perf_counter() - t0) * 1e3)
    return round(_median(samples), 3)


def main(argv) -> int:
    args = {"--model": "2pc4", "--fmax": "256", "--iters": "5",
            "--capacity": "16", "--out": None}
    it = iter(argv)
    for a in it:
        if a in ("-h", "--help"):
            print(__doc__)
            return 0
        if a not in args:
            raise SystemExit(f"unknown flag {a!r}")
        args[a] = next(it)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from stateright_tpu.checker.device_loop import shrink_indices
    from stateright_tpu.ops.expand import (eventually_indices,
                                           expand_frontier, pre_dedup)
    from stateright_tpu.ops.fused import build_fused_block_fn
    from stateright_tpu.ops.hash_kernel import fp64_device
    from stateright_tpu.ops.hashtable import _BUCKET, table_insert

    model = _make_model(args["--model"])
    fmax = int(args["--fmax"])
    iters = int(args["--iters"])
    capacity = 1 << int(args["--capacity"])
    backend = jax.default_backend()
    interpret = backend != "tpu"

    width = model.packed_width
    n_actions = model.max_actions
    fa = fmax * n_actions
    ev_idx = eventually_indices(model.properties())

    frontier = jnp.asarray(_frontier(model, fmax))
    ebits = jnp.zeros((fmax,), jnp.uint32)
    fvalid = jnp.ones((fmax,), bool)
    khi0 = jnp.zeros((capacity // _BUCKET, _BUCKET), jnp.uint32)
    klo0 = jnp.zeros((capacity // _BUCKET, _BUCKET), jnp.uint32)

    # --- staged stages, each standalone ------------------------------
    def stage_expand(rows):
        exp = expand_frontier(model, rows, fvalid, ebits, ev_idx,
                              child_fp=False)
        return exp.flat, exp.cvalid, exp.ebits

    def stage_hash(flat, cvalid):
        src = shrink_indices(cvalid, fa)
        rows_k = flat[src]
        return fp64_device(rows_k)

    def stage_dedup(chi, clo, cvalid):
        return pre_dedup(chi, clo, cvalid)

    def stage_probe(khi, klo, chi, clo, dvalid):
        return table_insert(khi, klo, chi, clo, dvalid)

    def staged_all(rows, khi, klo):
        flat, cvalid, _eb = stage_expand(rows)
        chi, clo = stage_hash(flat, cvalid)
        dvalid = stage_dedup(chi, clo, cvalid)
        return stage_probe(khi, klo, chi, clo, dvalid)

    j_expand = jax.jit(stage_expand)
    flat, cvalid, _ = j_expand(frontier)
    j_hash = jax.jit(stage_hash)
    chi, clo = j_hash(flat, cvalid)
    j_dedup = jax.jit(stage_dedup)
    dvalid = j_dedup(chi, clo, cvalid)
    j_probe = jax.jit(stage_probe)
    j_staged = jax.jit(staged_all)

    # --- fused kernel ------------------------------------------------
    fused_fn = jax.jit(build_fused_block_fn(
        model, fmax, capacity, symmetry=False, probe=True,
        interpret=interpret))

    stages = {
        "expand_ms": _timed(j_expand, (frontier,), iters),
        "hash_ms": _timed(j_hash, (flat, cvalid), iters),
        "pre_dedup_ms": _timed(j_dedup, (chi, clo, cvalid), iters),
        "probe_ms": _timed(j_probe, (khi0, klo0, chi, clo, dvalid),
                           iters),
    }
    staged_ms = _timed(j_staged, (frontier, khi0, klo0), iters)
    fused_ms = _timed(fused_fn, (frontier, ebits, fvalid, khi0, klo0),
                      iters)

    n_valid = int(np.asarray(cvalid).sum())
    n_dedup = int(np.asarray(dvalid).sum())
    line = {
        "model": args["--model"], "backend": backend,
        "interpret": interpret, "fmax": fmax,
        "lanes": fa, "valid_lanes": n_valid,
        "dup_lane_frac": round(1.0 - n_dedup / max(n_valid, 1), 4),
        "stages": stages,
        "staged_ms": staged_ms,
        "fused_ms": fused_ms,
        "fused_over_staged": round(fused_ms / staged_ms, 3)
        if staged_ms else None,
    }
    out = json.dumps(line)
    print(out)
    if args["--out"]:
        with open(args["--out"], "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
