"""Microbenchmark: staged expand→hash→dedup→probe vs the fused kernels.

Usage:
    python tools/kernel_bench.py [--model 2pc7|2pc4|paxos3] [--fmax N]
                                 [--iters N] [--capacity 2^k] [--out F]

Times ONE device iteration's dedup pipeline both ways, on a synthetic
frontier drawn from the model's real reachable states (BFS prefix):

  * **staged**, per stage — ``expand`` (``ops.expand.expand_frontier``,
    child fingerprints deferred), ``hash`` (``fp64_device`` over the
    compacted raw-valid lanes), ``pre_dedup`` (scatter-min claim arena),
    ``probe`` (``ops.hashtable.table_insert``) — each stage jitted
    standalone so the per-stage costs are visible, plus the composed
    staged pipeline in one jit (what the engines actually run);
  * **fused single-chip** (``ops.fused``): the one-kernel
    expand→fingerprint→props→pre-dedup→probe path (in-kernel property
    eval + the cross-chunk dedup ring, the production config);
  * **fused sharded two-kernel path**: the step kernel at the exchange
    boundary (``probe=False``) composed with the owner-side
    post-exchange probe kernel (``build_probe_block_fn``) — what a
    sharded fused chunk iteration dispatches around its all-to-all (the
    collective itself is excluded: this is a single-process microbench
    of the kernels, not the interconnect).

JSON fields (one line on stdout):
  ``stages.expand_ms/hash_ms/pre_dedup_ms/probe_ms`` — staged stages;
  ``stages.probe_kernel_ms`` — the owner-side probe kernel standalone,
  the direct A/B against ``stages.probe_ms`` at identical lanes/table;
  ``staged_ms``/``fused_ms``/``fused_over_staged`` — composed
  single-chip pipelines; ``sharded_staged_ms``/``sharded_fused_ms``/
  ``sharded_fused_over_staged`` — the sharded two-kernel path vs its
  staged equivalent (exchange excluded on both sides);
  ``dup_lane_frac`` — the workload's duplicate-lane fraction (the
  quantity the fusion attacks).

On non-TPU backends the fused paths run through the Pallas
**interpreter** — correctness-representative, NOT perf-representative;
the line carries ``"interpret": true`` so nobody reads a CPU ratio as a
TPU result.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _median(xs):
    s = sorted(xs)
    return s[len(s) // 2]


def _make_model(name: str):
    if name.startswith("2pc"):
        from stateright_tpu.models.twopc import TwoPhaseSys
        return TwoPhaseSys(int(name[3:]))
    if name.startswith("paxos"):
        from stateright_tpu.examples.paxos_packed import PackedPaxos
        return PackedPaxos(int(name[5:]))
    raise SystemExit(f"unknown --model {name!r} (want 2pcN or paxosN)")


def _frontier(model, fmax: int):
    """A real frontier slab: BFS from the inits until fmax rows exist
    (duplicate structure matters — a random frontier would understate
    the dedup stages)."""
    import numpy as np

    seen = set()
    rows = []
    queue = [s for s in model.init_states() if model.within_boundary(s)]
    while queue and len(rows) < fmax:
        state = queue.pop(0)
        fp = model.fingerprint(state)
        if fp in seen:
            continue
        seen.add(fp)
        rows.append(np.asarray(model.encode(state), np.uint32))
        for _a, nxt in model.next_steps(state):
            queue.append(nxt)
    while len(rows) < fmax:  # tiny models: tile the reached set
        rows.append(rows[len(rows) % max(len(seen), 1)])
    return np.stack(rows[:fmax])


def _timed(fn, args, iters: int):
    import jax

    out = fn(*args)  # compile + warm
    jax.block_until_ready(out)
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        samples.append((time.perf_counter() - t0) * 1e3)
    return round(_median(samples), 3)


def main(argv) -> int:
    args = {"--model": "2pc4", "--fmax": "256", "--iters": "5",
            "--capacity": "16", "--out": None}
    it = iter(argv)
    for a in it:
        if a in ("-h", "--help"):
            print(__doc__)
            return 0
        if a not in args:
            raise SystemExit(f"unknown flag {a!r}")
        args[a] = next(it)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from stateright_tpu.checker.device_loop import shrink_indices
    from stateright_tpu.ops.expand import (eventually_indices,
                                           expand_frontier, pre_dedup)
    from stateright_tpu.ops.fused import (build_fused_block_fn,
                                          build_probe_block_fn)
    from stateright_tpu.ops.hash_kernel import fp64_device
    from stateright_tpu.ops.hashtable import _BUCKET, table_insert

    model = _make_model(args["--model"])
    fmax = int(args["--fmax"])
    iters = int(args["--iters"])
    capacity = 1 << int(args["--capacity"])
    backend = jax.default_backend()
    interpret = backend != "tpu"

    width = model.packed_width
    n_actions = model.max_actions
    fa = fmax * n_actions
    props = len(model.properties()) > 0
    cc = 1 << 12  # a small production-shaped ring for the bench
    ev_idx = eventually_indices(model.properties())

    frontier = jnp.asarray(_frontier(model, fmax))
    ebits = jnp.zeros((fmax,), jnp.uint32)
    fvalid = jnp.ones((fmax,), bool)
    pfp0 = fp64_device(frontier)
    khi0 = jnp.zeros((capacity // _BUCKET, _BUCKET), jnp.uint32)
    klo0 = jnp.zeros((capacity // _BUCKET, _BUCKET), jnp.uint32)
    rhi0 = jnp.zeros((cc,), jnp.uint32)
    rlo0 = jnp.zeros((cc,), jnp.uint32)

    # --- staged stages, each standalone ------------------------------
    def stage_expand(rows):
        exp = expand_frontier(model, rows, fvalid, ebits, ev_idx,
                              child_fp=False)
        return exp.flat, exp.cvalid, exp.ebits

    def stage_hash(flat, cvalid):
        src = shrink_indices(cvalid, fa)
        rows_k = flat[src]
        return fp64_device(rows_k)

    def stage_dedup(chi, clo, cvalid):
        return pre_dedup(chi, clo, cvalid)

    def stage_probe(khi, klo, chi, clo, dvalid):
        return table_insert(khi, klo, chi, clo, dvalid)

    def staged_all(rows, khi, klo):
        flat, cvalid, _eb = stage_expand(rows)
        chi, clo = stage_hash(flat, cvalid)
        dvalid = stage_dedup(chi, clo, cvalid)
        return stage_probe(khi, klo, chi, clo, dvalid)

    j_expand = jax.jit(stage_expand)
    flat, cvalid, _ = j_expand(frontier)
    j_hash = jax.jit(stage_hash)
    chi, clo = j_hash(flat, cvalid)
    j_dedup = jax.jit(stage_dedup)
    dvalid = j_dedup(chi, clo, cvalid)
    j_probe = jax.jit(stage_probe)
    j_staged = jax.jit(staged_all)

    # --- fused single-chip kernel (props + cc, the production shape) --
    blk = build_fused_block_fn(
        model, fmax, capacity, symmetry=False, probe=True,
        interpret=interpret, props=props, cc=cc)

    def fused_one(rows, khi, klo, rhi, rlo):
        return blk(rows, ebits, fvalid, key_hi=khi, key_lo=klo,
                   pfp=pfp0 if props else None, ring=(rhi, rlo))

    fused_fn = jax.jit(fused_one)

    # --- the sharded two-kernel path: step kernel at the exchange
    # boundary + the owner-side probe kernel (exchange excluded) -------
    step_blk = build_fused_block_fn(
        model, fmax, 0, symmetry=False, probe=False,
        interpret=interpret, props=props, cc=cc)
    probe_blk = build_probe_block_fn(fa, capacity, interpret=interpret)

    def sharded_fused(rows, khi, klo, rhi, rlo):
        out = step_blk(rows, ebits, fvalid,
                       pfp=pfp0 if props else None, ring=(rhi, rlo))
        return probe_blk(out.chi, out.clo, out.dvalid, khi, klo)

    j_sharded_fused = jax.jit(sharded_fused)
    # its staged equivalent is the composed staged pipeline (the real
    # sharded staged path interleaves the exchange between dedup and
    # probe; the op content is identical)
    j_sharded_staged = j_staged

    def probe_kernel_one(khi, klo, chi_, clo_, dvalid_):
        return probe_blk(chi_, clo_, dvalid_, khi, klo)

    j_probe_kernel = jax.jit(probe_kernel_one)

    stages = {
        "expand_ms": _timed(j_expand, (frontier,), iters),
        "hash_ms": _timed(j_hash, (flat, cvalid), iters),
        "pre_dedup_ms": _timed(j_dedup, (chi, clo, cvalid), iters),
        "probe_ms": _timed(j_probe, (khi0, klo0, chi, clo, dvalid),
                           iters),
        # the owner-side probe kernel, same lanes/table as probe_ms —
        # the direct per-stage A/B the sharded fused path rides
        "probe_kernel_ms": _timed(
            j_probe_kernel, (khi0, klo0, chi, clo, dvalid), iters),
    }
    staged_ms = _timed(j_staged, (frontier, khi0, klo0), iters)
    fused_ms = _timed(fused_fn, (frontier, khi0, klo0, rhi0, rlo0),
                      iters)
    sharded_staged_ms = _timed(j_sharded_staged,
                               (frontier, khi0, klo0), iters)
    sharded_fused_ms = _timed(j_sharded_fused,
                              (frontier, khi0, klo0, rhi0, rlo0),
                              iters)

    n_valid = int(np.asarray(cvalid).sum())
    n_dedup = int(np.asarray(dvalid).sum())
    line = {
        "model": args["--model"], "backend": backend,
        "interpret": interpret, "fmax": fmax,
        "lanes": fa, "valid_lanes": n_valid,
        "dup_lane_frac": round(1.0 - n_dedup / max(n_valid, 1), 4),
        "stages": stages,
        "staged_ms": staged_ms,
        "fused_ms": fused_ms,
        "fused_over_staged": round(fused_ms / staged_ms, 3)
        if staged_ms else None,
        "sharded_staged_ms": sharded_staged_ms,
        "sharded_fused_ms": sharded_fused_ms,
        "sharded_fused_over_staged": round(
            sharded_fused_ms / sharded_staged_ms, 3)
        if sharded_staged_ms else None,
    }
    out = json.dumps(line)
    print(out)
    if args["--out"]:
        with open(args["--out"], "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
