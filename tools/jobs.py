"""CLI for the checking-as-a-service job API (stateright_tpu/service).

Server:
    python tools/jobs.py serve --root DIR [--host H] [--port P]
        [--devices N] [--cpu] [--cpu-devices N]
        Runs the scheduler + HTTP API until interrupted. Prints ONE
        ready line to stdout (``jobs-service listening on URL``) so
        wrappers can scrape the ephemeral port. ``--cpu`` forces
        JAX_PLATFORMS=cpu (with ``--cpu-devices N`` virtual devices)
        BEFORE jax initializes — the no-hardware smoke path.

Client (all take --url http://host:port):
    python tools/jobs.py submit --url U --model NAME [--args 3,2]
        [--width W] [--priority P] [--target N] [--options '{"k":v}']
        [--step-delay S] [--batch] [--kind soak|fuzz]
        [--kwargs '{"k":v}']                  -> prints the job id
        ``--batch`` opts the job into the batch lane engine
        (JobSpec batch='auto'): same-bucket small jobs coalesce into
        one vmapped chunk program; ``list`` shows the batch/lane a
        batched job ran on. ``--kind soak|fuzz`` runs a chaos
        soak/fuzz job instead of a checking job: --model names a
        SOAK_REGISTRY config (write_once, abd, write_once_volatile)
        and ``--kwargs`` carries SoakConfig overrides
        (README § Continuous verification)
    python tools/jobs.py list --url U
    python tools/jobs.py watch --url U JOB [--timeout S]
        polls until the job is terminal or paused; prints transitions
    python tools/jobs.py result --url U JOB  -> prints result.json
    python tools/jobs.py pause|resume|cancel --url U JOB

Models are the named registry in ``stateright_tpu/service/jobs.py``
(twopc, paxos, single_copy, abd) — specs are plain JSON, so none of
this pickles anything.
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _arg(argv, flag, default=None):
    if flag in argv:
        return argv[argv.index(flag) + 1]
    return default


def _http(url: str, payload=None, timeout: float = 30.0):
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {},
        method="POST" if data is not None else "GET")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _post(url: str, payload=None):
    return _http(url, payload if payload is not None else {})


def cmd_serve(argv) -> int:
    if "--cpu" in argv:
        os.environ["JAX_PLATFORMS"] = "cpu"
        n = int(_arg(argv, "--cpu-devices", "2"))
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={n}"
            ).strip()
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # a sitecustomize may have overridden the *config* (not just
        # the env var); re-assert the requested platform
        jax.config.update("jax_platforms",
                          os.environ["JAX_PLATFORMS"])

    from stateright_tpu.service import JobStore, Scheduler, serve_jobs

    root = _arg(argv, "--root")
    if not root:
        print("serve requires --root DIR", file=sys.stderr)
        return 2
    host = _arg(argv, "--host", "127.0.0.1")
    port = int(_arg(argv, "--port", "0"))
    devices = jax.devices()
    limit = _arg(argv, "--devices")
    if limit:
        devices = devices[:int(limit)]
    scheduler = Scheduler(JobStore(root), devices=devices)
    handle = serve_jobs(scheduler, (host, port), block=False)
    print(f"jobs-service listening on {handle.url} root={root} "
          f"devices={len(devices)}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        handle.shutdown()
    return 0


def _parse_args_list(raw):
    if not raw:
        return []
    out = []
    for tok in str(raw).split(","):
        tok = tok.strip()
        try:
            out.append(int(tok))
        except ValueError:
            out.append(tok)
    return out


def cmd_submit(argv) -> int:
    url = _arg(argv, "--url")
    model = _arg(argv, "--model")
    if not url or not model:
        print("submit requires --url and --model", file=sys.stderr)
        return 2
    payload = {
        "model": model,
        "args": _parse_args_list(_arg(argv, "--args")),
        "options": json.loads(_arg(argv, "--options", "{}")),
        "priority": int(_arg(argv, "--priority", "0")),
        "width": int(_arg(argv, "--width", "1")),
        "step_delay": float(_arg(argv, "--step-delay", "0")),
    }
    target = _arg(argv, "--target")
    if target:
        payload["target"] = int(target)
    if "--batch" in argv:
        payload["batch"] = "auto"
    kind = _arg(argv, "--kind")
    if kind:
        # soak|fuzz: --model names a SOAK_REGISTRY config and --kwargs
        # carries SoakConfig overrides (README § Continuous
        # verification)
        payload["kind"] = kind
    kwargs = _arg(argv, "--kwargs")
    if kwargs:
        payload["kwargs"] = json.loads(kwargs)
    out = _post(url.rstrip("/") + "/jobs", payload)
    print(out["id"])
    return 0


def cmd_list(argv) -> int:
    url = _arg(argv, "--url")
    out = _http(url.rstrip("/") + "/jobs")
    for job in out["jobs"]:
        lane = (f" batch={job['batch']}/lane{job['lane']}"
                if "batch" in job and "lane" in job else "")
        kind = f" kind={job['kind']}" if job.get("kind") else ""
        if job.get("burnin"):
            kind += " burnin"
        print(f"{job['id']:28} {job['state']:10} "
              f"prio={job.get('priority', 0)} "
              f"width={job.get('granted_width', job.get('width'))} "
              f"model={job.get('model')}{kind}{lane}")
    prof = out.get("profile") or {}
    if prof:
        print("# " + " ".join(f"{k}={v}" for k, v in sorted(
            prof.items())))
    return 0


TERMINAL = ("done", "failed", "cancelled")


def cmd_watch(argv) -> int:
    url = _arg(argv, "--url").rstrip("/")
    job_id = [a for a in argv if not a.startswith("--")
              and a not in (url, _arg(argv, "--timeout") or "")][-1]
    deadline = time.monotonic() + float(_arg(argv, "--timeout", "300"))
    last = None
    while time.monotonic() < deadline:
        view = _http(f"{url}/jobs/{job_id}")
        state = view.get("state")
        if state != last:
            print(f"{job_id}: {state}", flush=True)
            last = state
        if state in TERMINAL or state == "paused":
            return 0 if state in ("done", "paused") else 1
        time.sleep(0.2)
    print(f"{job_id}: timeout (last state {last})", file=sys.stderr)
    return 1


def cmd_result(argv) -> int:
    url = _arg(argv, "--url").rstrip("/")
    job_id = [a for a in argv[1:] if not a.startswith("--")
              and a != url][-1]
    view = _http(f"{url}/jobs/{job_id}")
    result = view.get("result")
    if result is None:
        print(json.dumps(view, indent=1, default=str))
        return 1
    print(json.dumps(result, indent=1, default=str))
    return 0


def _cmd_control(argv, action: str) -> int:
    url = _arg(argv, "--url").rstrip("/")
    job_id = [a for a in argv[1:] if not a.startswith("--")
              and a != url][-1]
    out = _post(f"{url}/jobs/{job_id}/{action}")
    print(json.dumps(out))
    return 0 if out.get("ok") else 1


def main(argv) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    cmd = argv[0]
    if cmd == "serve":
        return cmd_serve(argv)
    if cmd == "submit":
        return cmd_submit(argv)
    if cmd == "list":
        return cmd_list(argv)
    if cmd == "watch":
        return cmd_watch(argv)
    if cmd == "result":
        return cmd_result(argv)
    if cmd in ("pause", "resume", "cancel"):
        return _cmd_control(argv, cmd)
    print(f"unknown command {cmd!r}; see --help", file=sys.stderr)
    return 2


if __name__ == "__main__":
    try:
        raise SystemExit(main(sys.argv[1:]))
    except BrokenPipeError:  # e.g. `jobs.py result ... | head`
        raise SystemExit(0)
