"""Profile the device chunk loop on paxos: trace one warm capped run and
summarize (a) the engine's own run-trace via the span consumer
(tools/stall_report.py — the overlap-aware stall attribution table)
and (b) op time by kernel name from the XLA trace proto — the stall
table explains WHICH side blocked the wall clock, the XLA trace WHERE
the device time went. A thin shim: all trace parsing lives in
stall_report/obs.spans."""
import glob
import gzip
import json
import os
import shutil
import sys
import time

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RUN_TRACE = "/tmp/jaxprof/run_trace.jsonl"


def run(cap=500_000, trace=None):
    import os
    if os.environ.get("PROF_MODEL") == "2pc":
        from stateright_tpu.models.twopc import TwoPhaseSys
        t0 = time.perf_counter()
        ck = (TwoPhaseSys(7).checker()
              .tpu_options(capacity=1 << 22, trace=trace)
              .spawn_tpu().join())
        dt = time.perf_counter() - t0
        print(f"run: {ck.unique_state_count()} uniq in {dt:.2f}s "
              f"({ck.unique_state_count()/dt:,.0f}/s)", file=sys.stderr)
        return
    from stateright_tpu.examples.paxos_packed import PackedPaxos
    t0 = time.perf_counter()
    ck = (PackedPaxos(3).checker()
          .tpu_options(capacity=1 << 21, race=False, trace=trace)
          .target_state_count(cap)
          .spawn_tpu().join())
    dt = time.perf_counter() - t0
    print(f"run: {ck.unique_state_count()} uniq in {dt:.2f}s "
          f"({ck.unique_state_count()/dt:,.0f}/s) "
          f"profile={ {k: round(v, 3) if isinstance(v, float) else v
                       for k, v in ck.profile().items()} }",
          file=sys.stderr)


outdir = "/tmp/jaxprof"
shutil.rmtree(outdir, ignore_errors=True)
os.makedirs(outdir, exist_ok=True)
run()  # warm (compile-cache load)
run()  # warm (observed-size-memo shape switch)
with jax.profiler.trace(outdir):
    run(trace=RUN_TRACE)

# --- the engine's own run-trace: overlap-aware stall attribution --------
import stall_report  # noqa: E402
from trace_report import load_events  # noqa: E402

print("\n=== stall attribution ===", file=sys.stderr)
_attr, _imb = stall_report.attribution_from_events(
    load_events(RUN_TRACE))
stall_report.render(_attr, _imb, title=RUN_TRACE, out=sys.stderr)

# --- XLA kernel-time table ---------------------------------------------
traces = glob.glob(os.path.join(outdir, "**", "*.trace.json.gz"),
                   recursive=True)
print("traces:", traces, file=sys.stderr)
ev_by_name = {}
for t in traces:
    with gzip.open(t, "rt") as f:
        data = json.load(f)
    for ev in data.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "?")
        dur = ev.get("dur", 0)  # us
        ev_by_name.setdefault(name, [0, 0])
        ev_by_name[name][0] += dur
        ev_by_name[name][1] += 1
top = sorted(ev_by_name.items(), key=lambda kv: -kv[1][0])[:45]
for name, (dur, cnt) in top:
    print(f"{dur/1e3:10.1f} ms  x{cnt:<6} {name[:110]}")
