"""Profile the device chunk loop on paxos: trace one warm capped run and
summarize op time by kernel name from the trace proto."""
import glob
import gzip
import json
import os
import shutil
import sys
import time

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(cap=500_000):
    import os
    if os.environ.get("PROF_MODEL") == "2pc":
        from stateright_tpu.models.twopc import TwoPhaseSys
        t0 = time.perf_counter()
        ck = (TwoPhaseSys(7).checker()
              .tpu_options(capacity=1 << 22)
              .spawn_tpu().join())
        dt = time.perf_counter() - t0
        print(f"run: {ck.unique_state_count()} uniq in {dt:.2f}s "
              f"({ck.unique_state_count()/dt:,.0f}/s)", file=sys.stderr)
        return
    from stateright_tpu.examples.paxos_packed import PackedPaxos
    t0 = time.perf_counter()
    ck = (PackedPaxos(3).checker()
          .tpu_options(capacity=1 << 21, race=False)
          .target_state_count(cap)
          .spawn_tpu().join())
    dt = time.perf_counter() - t0
    print(f"run: {ck.unique_state_count()} uniq in {dt:.2f}s "
          f"({ck.unique_state_count()/dt:,.0f}/s) "
          f"profile={ {k: round(v, 3) for k, v in ck.profile().items()} }",
          file=sys.stderr)


outdir = "/tmp/jaxprof"
shutil.rmtree(outdir, ignore_errors=True)
run()  # warm (compile-cache load)
run()  # warm (observed-size-memo shape switch)
with jax.profiler.trace(outdir):
    run()

traces = glob.glob(os.path.join(outdir, "**", "*.trace.json.gz"),
                   recursive=True)
print("traces:", traces, file=sys.stderr)
ev_by_name = {}
for t in traces:
    with gzip.open(t, "rt") as f:
        data = json.load(f)
    for ev in data.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "?")
        dur = ev.get("dur", 0)  # us
        ev_by_name.setdefault(name, [0, 0])
        ev_by_name[name][0] += dur
        ev_by_name[name][1] += 1
top = sorted(ev_by_name.items(), key=lambda kv: -kv[1][0])[:45]
for name, (dur, cnt) in top:
    print(f"{dur/1e3:10.1f} ms  x{cnt:<6} {name[:110]}")
