"""Live fleet console for the job service: pool, queue, jobs, SLOs.

Usage:
    python tools/fleetboard.py --url http://host:port [--interval 2]
    python tools/fleetboard.py SERVICE_ROOT [--once]

One frame per interval (or one frame with ``--once``):

    == fleetboard 12:00:01  jobs run=2 queued=1 done=5 failed=0 ...
    pool  62% busy  [0] ####---- 4/8   [1] ##------ 2/8   trend _.:=+#
    jobs:
      j0003-twopc  running  w=2 host=0  uniq=12,345  +8.2k/s
    slo: queue_wait 0.41s/job  first_chunk 1.92s/job
    interventions: preemptions=1 retries=0 sse_dropped=0

``--url`` polls the service HTTP API (``GET /jobs`` +
``GET /utilization``); a SERVICE_ROOT argument reads the durable
artifacts offline (job directories + ``service.jsonl`` via
``tools/watch.py``'s file follower) — the postmortem twin of the live
board. Rendering reuses ``tools/watch.py``'s console sources
(rate formatting, JSONL tailing); per-job throughput is the delta of
``unique`` between frames.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import watch  # noqa: E402  (the shared console sources)

#: ASCII sparkline levels for the busy-fraction trend
_SPARK = "_.:-=+*#"


def spark(values: List[float]) -> str:
    """An ASCII sparkline of 0..1 values."""
    out = []
    for v in values:
        v = min(max(float(v), 0.0), 1.0)
        out.append(_SPARK[min(int(v * len(_SPARK)), len(_SPARK) - 1)])
    return "".join(out)


def _bar(busy_frac: float, width: int = 8) -> str:
    filled = int(round(busy_frac * width))
    return "#" * filled + "-" * (width - filled)


class Board:
    """Stateful frame renderer: feed() it snapshots, get frames back.

    A snapshot is ``{"jobs": [job views], "profile": scheduler
    profile, "utilization": {...}}`` — exactly what the HTTP API
    serves, so the offline reader fabricates the same shape."""

    def __init__(self):
        self._prev_uniq: Dict[str, int] = {}
        self._prev_t: Optional[float] = None
        self.frames = 0

    def feed(self, snap: Dict[str, Any]) -> str:
        now = time.time()
        jobs = snap.get("jobs") or []
        prof = snap.get("profile") or {}
        util = snap.get("utilization") or {}
        by_state: Dict[str, int] = {}
        for j in jobs:
            by_state[j.get("state", "?")] = \
                by_state.get(j.get("state", "?"), 0) + 1
        lines = [
            "== fleetboard {}  jobs run={} queued={} paused={} done={}"
            " failed={}  depth={}  {} jobs/min".format(
                time.strftime("%H:%M:%S"),
                by_state.get("running", 0), by_state.get("queued", 0),
                by_state.get("paused", 0), by_state.get("done", 0),
                by_state.get("failed", 0),
                int(util.get("queue_depth",
                             prof.get("queue_depth", 0)) or 0),
                int(prof.get("jobs_per_min", 0) or 0))]
        # pool occupancy + per-host bars + busy trend
        per_host = util.get("per_host") or {}
        busy = util.get("busy_frac")
        if busy is not None:
            hw = (util.get("width", 0) // max(len(per_host), 1)
                  if per_host else util.get("width", 0))
            bars = "   ".join(
                f"[{h}] {_bar(f)} {f:.0%}"
                for h, f in sorted(per_host.items()))
            trend = [s.get("busy_frac", 0.0)
                     for s in (util.get("samples") or [])[-32:]]
            line = f"pool  {busy:4.0%} busy  {bars}"
            if trend:
                line += f"   trend {spark(trend)}"
            lines.append(line)
        # quarantined chips (silent-corruption defense): devices the
        # auditor caught lying, withheld from every grant until an
        # audit probe passes — a shrunken pool must say why
        quarantined = util.get("quarantined") or []
        if quarantined or prof.get("quarantined"):
            lines.append(
                "quarantine: {} device(s) withheld{}".format(
                    len(quarantined) or int(prof.get("quarantined", 0)),
                    ("  [" + ", ".join(map(str, quarantined)) + "]")
                    if quarantined else ""))
        # per-job rows with throughput deltas
        active = [j for j in jobs
                  if j.get("state") in ("running", "queued", "paused")]
        if active:
            lines.append("jobs:")
        dt = (now - self._prev_t) if self._prev_t is not None else None
        for j in active:
            jid = j.get("id", "?")
            row = (f"  {jid:<24} {j.get('state', '?'):<8} "
                   f"w={j.get('granted_width', j.get('width', '?'))}")
            hosts = j.get("hosts")
            if hosts:
                row += f" host={','.join(map(str, hosts))}"
            if j.get("batch"):
                row += f" batch={j['batch']}/l{j.get('lane')}"
            if j.get("kind") in ("soak", "fuzz"):
                # soak/fuzz lane rows: kind + burn-in tag, ops instead
                # of unique states, and the cross-check verdict
                row += f" {j['kind']}"
                if j.get("burnin"):
                    row += "(burnin)"
                ops = (j.get("result") or {}).get(
                    "completed", j.get("ops_completed"))
                if ops is not None:
                    row += f"  ops={int(ops):,}"
                    prev = self._prev_uniq.get(jid)
                    if prev is not None and dt and dt > 0:
                        row += (f"  +{watch.Console._rate((int(ops) - prev) / dt)}"
                                "/s")
                    self._prev_uniq[jid] = int(ops)
                if j.get("history_ok") is False:
                    row += "  VIOLATION"
                lines.append(row)
                continue
            uniq = (j.get("result") or {}).get("unique_state_count",
                                               j.get("unique"))
            if uniq is not None:
                row += f"  uniq={int(uniq):,}"
                prev = self._prev_uniq.get(jid)
                if prev is not None and dt and dt > 0:
                    rate = (int(uniq) - prev) / dt
                    row += f"  +{watch.Console._rate(rate)}/s"
                self._prev_uniq[jid] = int(uniq)
            lines.append(row)
        # burn-in lane summary: the background soak/fuzz load must be
        # visible, not invisible (README § Continuous verification)
        burn = util.get("burnin_frac", prof.get("burnin_frac"))
        if burn or prof.get("soak_jobs") or prof.get("violations"):
            parts = []
            if burn is not None:
                parts.append(f"{float(burn):.0%} of pool")
            for key in ("soak_jobs", "fuzz_ops", "violations"):
                if prof.get(key):
                    parts.append(f"{key}={int(prof[key])}")
            lines.append("burnin: " + "  ".join(parts))
        # top-stall line (obs/spans.py attribution): summed non-overlap
        # buckets across job result profiles (plus any service-level
        # attribution the offline reader derived from span events) —
        # the fleet's dominant stall and mean pipeline bubble
        stalls: Dict[str, float] = {}
        bubbles: List[float] = []
        sources = [((j.get("result") or {}).get("profile") or {})
                   for j in jobs]
        sources.append(prof)
        for p in sources:
            attr = p.get("attribution")
            if isinstance(attr, dict):
                for k, v in attr.items():
                    if k != "overlap":
                        stalls[k] = stalls.get(k, 0.0) + float(v)
            if p.get("bubble_frac") is not None:
                bubbles.append(float(p["bubble_frac"]))
        if stalls:
            top = sorted(stalls.items(), key=lambda kv: (-kv[1], kv[0]))
            line = "stall: " + "  ".join(
                f"{k}={v:.2f}s" for k, v in top[:3])
            if bubbles:
                line += (f"  bubble={sum(bubbles) / len(bubbles):.0%}"
                         f" mean")
            lines.append(line)
        # SLO aggregates (cumulative seconds / completions)
        done = by_state.get("done", 0) or int(prof.get("jobs_done",
                                                       0) or 0)
        slo = []
        if prof.get("queue_wait_s") is not None:
            denom = max(int(prof.get("jobs_submitted", done) or 1), 1)
            slo.append(
                f"queue_wait {prof['queue_wait_s'] / denom:.2f}s/job")
        if prof.get("first_chunk_s") is not None and done:
            slo.append(
                f"first_chunk {prof['first_chunk_s'] / done:.2f}s/job")
        if slo:
            lines.append("slo: " + "  ".join(slo))
        inter = {k: int(prof[k]) for k in
                 ("preemptions", "retries", "degrades", "promotes",
                  "demotes", "spills",
                  "audits", "audit_mismatches", "quarantined",
                  "jobs_failed", "sse_dropped", "recorder_dumps")
                 if prof.get(k)}
        lines.append("interventions: " + (" ".join(
            f"{k}={v}" for k, v in sorted(inter.items()))
            if inter else "none"))
        self._prev_t = now
        self.frames += 1
        return "\n".join(lines) + "\n"


# --- snapshot sources -------------------------------------------------------

def poll_url(url: str) -> Dict[str, Any]:
    """One live snapshot from the service HTTP API."""
    import urllib.request
    base = url.rstrip("/")
    with urllib.request.urlopen(base + "/jobs") as r:
        jobs_payload = json.loads(r.read())
    with urllib.request.urlopen(base + "/utilization") as r:
        util = json.loads(r.read())
    return {"jobs": jobs_payload.get("jobs") or [],
            "profile": jobs_payload.get("profile") or {},
            "utilization": util}


def load_offline(root: str) -> Dict[str, Any]:
    """One snapshot from a service root's durable artifacts: job
    status/result files plus the ``service.jsonl`` event stream
    (tailed through ``watch.follow_file``) for the profile-ish counts
    and the last pool_util sample."""
    from stateright_tpu.service.jobs import JobStore
    store = JobStore(root)
    jobs = [j.view() for j in store.jobs()]
    profile: Dict[str, Any] = {}
    util: Dict[str, Any] = {}
    samples: List[Dict[str, Any]] = []
    svc = store.service_trace_path
    if os.path.isfile(svc):
        counts: Dict[str, int] = {}
        span_events: List[Dict[str, Any]] = []
        for ev in watch.follow_file(svc, follow=False):
            kind = ev.get("ev")
            counts[kind] = counts.get(kind, 0) + 1
            if kind == "span":
                span_events.append(ev)
            if kind == "pool_util":
                util.update({"busy_frac": ev.get("busy_frac"),
                             "per_host": ev.get("per_host") or {},
                             "queue_depth": ev.get("queue_depth", 0),
                             "burnin_frac": ev.get("burnin_frac")})
                samples.append({"busy_frac": ev.get("busy_frac", 0.0)})
            elif kind == "job_pause" \
                    and ev.get("reason") == "preempt":
                profile["preemptions"] = \
                    profile.get("preemptions", 0) + 1
            elif kind == "job_promote":
                profile["promotes"] = \
                    profile.get("promotes", 0) + 1
            elif kind == "job_demote":
                profile["demotes"] = \
                    profile.get("demotes", 0) + 1
            elif kind == "quarantine":
                # the LAST quarantine event carries the current count
                # (re-admission probes emit one too, with the count
                # after the release), and its device key when present
                profile["quarantined"] = ev.get("quarantined", 0)
                dev = ev.get("device")
                qset = set(util.get("quarantined") or [])
                if ev.get("probe") == "pass":
                    qset.discard(str(dev))
                elif dev is not None:
                    qset.add(str(dev))
                util["quarantined"] = sorted(qset)
        profile["jobs_submitted"] = counts.get("job_submit", 0)
        profile["jobs_done"] = sum(
            1 for j in jobs if j.get("state") == "done")
        profile["soak_jobs"] = sum(
            1 for j in jobs if j.get("state") == "done"
            and j.get("kind") in ("soak", "fuzz"))
        profile["violations"] = sum(
            1 for j in jobs if j.get("history_ok") is False)
        wait = [((j.get("result") or {}).get("lifecycle") or {})
                .get("queue_wait_s") for j in jobs]
        wait = [w for w in wait if w is not None]
        if wait:
            profile["queue_wait_s"] = sum(wait)
        if span_events:
            # service-stream spans (batch lane engine, queue-wait idle
            # gaps): fold into the board's stall line
            from stateright_tpu.obs import spans as spans_mod
            attr = spans_mod.analyze(
                spans_mod.spans_from_events(span_events))
            if attr["spans"]:
                profile["attribution"] = {
                    k: round(v, 6)
                    for k, v, _s in spans_mod.ranked(attr)}
                profile["bubble_frac"] = round(attr["bubble_frac"], 6)
        util["samples"] = samples
    return {"jobs": jobs, "profile": profile, "utilization": util}


def main(argv) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    once = "--once" in argv
    interval = 2.0
    if "--interval" in argv:
        interval = float(argv[argv.index("--interval") + 1])
    url = None
    if "--url" in argv:
        url = argv[argv.index("--url") + 1]
    paths = [a for a in argv if not a.startswith("--")
             and (not url or a != url)]
    board = Board()
    try:
        while True:
            if url is not None:
                snap = poll_url(url)
            elif paths:
                snap = load_offline(paths[0])
            else:
                print("fleetboard: need --url or a service root",
                      file=sys.stderr)
                return 2
            sys.stdout.write(board.feed(snap))
            sys.stdout.flush()
            if once:
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
