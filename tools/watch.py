"""Live terminal console for a checking run.

Usage:
    python tools/watch.py TRACE.jsonl            # tail a growing trace
    python tools/watch.py TRACE.jsonl --once     # render + exit at EOF
    python tools/watch.py --url http://host:port # attach to an Explorer

Renders the run-trace event stream (``stateright_tpu.obs.EVENT_SCHEMA``)
as a scrolling console: per-chunk progress lines with unique-state
throughput, dedup hit-rate, table load factor, queue depth and the
device/transfer time split, plus one line per intervention — growth and
kovf resizes, compiles, the resilience layer's
retry/watchdog/autosave/failover/degrade events, fused-kernel
fallbacks, flight-recorder dumps, and the soak harness's live
crash/restart/partition injections — and the discovery/done/error
endings.

Three attachment modes, one renderer:

* **tail mode** (a path): follows a growing JSONL file the way
  ``tail -f`` would, rendering each event as it lands; with ``--once``
  it renders the current contents and exits (postmortem reading);
* **Explorer mode** (``--url``): consumes ``GET /.events`` — the SSE
  stream replays the flight-recorder backlog first, so attaching late
  still shows the run so far;
* **in-process mode** (:func:`attach`): subscribes a console directly
  to a live checker's trace — the programmatic twin the tests (and
  notebooks) use: ``watch.attach(checker)`` blocks rendering until the
  run completes.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, Iterable, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: event kinds rendered as one-line interventions (everything that is
#: not periodic progress); unknown kinds also land here so a consumer
#: never silently swallows a new event type
_PROGRESS = ("chunk", "level", "progress")
_QUIET = ("run_start", "done", "error", "discovery", "ops")


class Console:
    """Stateful event-stream renderer: feed() it event dicts in order.

    ``interval`` throttles progress lines (seconds between renders;
    0 renders every progress event — what the tests use for
    determinism). Throughput is computed from the trace's own
    timestamps, so replaying a recorded file shows the run's real
    rates, not the replay speed."""

    def __init__(self, out=None, interval: float = 0.0):
        self.out = sys.stdout if out is None else out
        self.interval = interval
        self._last_render_t: Optional[float] = None
        self._last_unique = 0
        self._last_t = 0.0
        self._dev_total = 0.0
        self._xfer_total = 0.0
        # recent span events (obs/spans.py): bounded, folded into a
        # live top-stall fragment on progress lines rather than
        # rendered per-event (spans arrive several per chunk)
        from collections import deque
        self._spans: "deque" = deque(maxlen=512)
        self.rendered_progress = 0
        self.rendered_events = 0

    # --- rendering helpers ---------------------------------------------
    def _w(self, line: str) -> None:
        self.out.write(line + "\n")
        try:
            self.out.flush()
        except (ValueError, OSError):
            pass

    @staticmethod
    def _rate(n: float) -> str:
        if n >= 1e6:
            return f"{n / 1e6:.2f}M"
        if n >= 1e3:
            return f"{n / 1e3:.1f}k"
        return f"{n:.0f}"

    def _progress_line(self, ev: Dict[str, Any]) -> None:
        t = float(ev.get("t", 0.0))
        unique = ev.get("unique")
        parts = [f"t={t:8.2f}s"]
        if unique is not None:
            dt = max(t - self._last_t, 1e-9)
            rate = (unique - self._last_unique) / dt
            parts.append(f"uniq={unique:>10,}")
            parts.append(f"({self._rate(rate):>9} uniq/s)")
            self._last_unique, self._last_t = unique, t
        if "dedup_hit" in ev:
            parts.append(f"dedup={ev['dedup_hit']:.3f}")
        if "load" in ev:
            parts.append(f"load={ev['load']:.3f}")
        if "q_size" in ev:
            parts.append(f"q={ev['q_size']:>8,}")
        if ev.get("device_s") is not None:
            self._dev_total += ev["device_s"]
            self._xfer_total += ev.get("xfer_s") or 0.0
            if t > 0:
                parts.append(f"dev={self._dev_total / t:4.0%}")
                parts.append(f"xfer={self._xfer_total / t:4.0%}")
        if "shard_q" in ev:
            parts.append(f"shards={len(ev['shard_q'])}")
        stall = self._top_stall()
        if stall:
            parts.append(stall)
        self._w(" ".join(parts))
        self.rendered_progress += 1

    def _top_stall(self) -> Optional[str]:
        """Live top-stall fragment from the recent span window: the
        largest NON-overlap attribution bucket (overlap is the
        pipeline working — not a stall) plus the bubble fraction."""
        if not self._spans:
            return None
        from stateright_tpu.obs import spans as spans_mod
        attr = spans_mod.analyze(self._spans)
        rows = [r for r in spans_mod.ranked(attr) if r[0] != "overlap"]
        if not rows:
            return f"stall=none bubble={attr['bubble_frac']:.0%}"
        name, _secs, share = rows[0]
        return (f"stall={name}:{share:.0%} "
                f"bubble={attr['bubble_frac']:.0%}")

    def _event_line(self, ev: Dict[str, Any]) -> None:
        detail = " ".join(
            f"{k}={v}" for k, v in ev.items()
            if k not in ("t", "ev", "engine"))
        self._w(f"t={float(ev.get('t', 0.0)):8.2f}s !! "
                f"{ev.get('ev', '?'):<14} {detail}")
        self.rendered_events += 1

    # --- the consumer entry point --------------------------------------
    def feed(self, ev: Dict[str, Any]) -> None:
        kind = ev.get("ev")
        if kind == "span":
            # accumulated for the progress lines' top-stall fragment,
            # never rendered per-event (several land per chunk)
            self._spans.append(ev)
            return
        if kind in _PROGRESS:
            now = time.monotonic()
            if (self.interval and self._last_render_t is not None
                    and now - self._last_render_t < self.interval):
                # throttled; rates recompute from the trace timestamps
                # at the next rendered event, so nothing is lost
                return
            self._last_render_t = now
            self._progress_line(ev)
        elif kind == "run_start":
            self._w(f"== run_start model={ev.get('model')} "
                    f"engine={ev.get('engine')} "
                    f"properties={ev.get('properties')}")
        elif kind == "discovery":
            self._w(f"t={float(ev.get('t', 0.0)):8.2f}s ** discovered "
                    f"{ev.get('property')!r}")
        elif kind == "done":
            self._w(f"== done gen={ev.get('gen')} "
                    f"unique={ev.get('unique')} "
                    f"discoveries={ev.get('discoveries')}")
        elif kind == "error":
            self._w(f"== ERROR {ev.get('error')}")
        elif kind == "ops":
            self._w(f"t={float(ev.get('t', 0.0)):8.2f}s ops "
                    f"invoked={ev.get('op_invoke')} "
                    f"returned={ev.get('op_return')} "
                    f"timeouts={ev.get('op_timeouts')}")
        else:
            # growth/resize, resilience, fused, recorder, soak faults —
            # and any future event kind: always visible
            self._event_line(ev)


# --- event sources ---------------------------------------------------------

def follow_file(path, follow: bool = True,
                poll: float = 0.2) -> Iterable[Dict[str, Any]]:
    """Yield events from a JSONL trace; with ``follow`` keep tailing
    the growing file (stop after a ``done``/``error`` event has been
    seen and the file stops growing)."""
    ended = False
    with open(path) as f:
        while True:
            line = f.readline()
            if line:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue  # a partially-written trailing line
                if ev.get("ev") in ("done", "error"):
                    ended = True
                yield ev
                continue
            if not follow or ended:
                return
            time.sleep(poll)


def follow_url(url: str, reconnect: bool = True, retries: int = 5,
               base_delay: float = 0.5, max_delay: float = 8.0,
               _sleep=time.sleep,
               _rng=None) -> Iterable[Dict[str, Any]]:
    """Yield events from an SSE stream (Explorer ``GET /.events`` or a
    service job's ``/events``), surviving dropped connections.

    A dropped connection (server restart, proxy timeout, network blip)
    used to END the console mid-run. Now the client reconnects with
    jittered exponential backoff (up to ``retries`` consecutive
    failures); the server replays its flight-ring backlog on
    reconnect, and a bounded already-seen window (sized past the
    flight ring, so the whole replay is coverable) suppresses events
    this generator already yielded — the console resumes exactly where
    it left off, without duplicated lines. The retry counter resets
    whenever a connection delivers events, so a long flaky run is
    bounded per-outage, not per-lifetime.

    Ends when a terminal ``done``/``error`` event has been seen and
    the stream closes, when a clean close delivers nothing new (a
    finished trace replay), or when ``retries`` consecutive attempts
    fail. ``_sleep``/``_rng`` are test seams."""
    import http.client
    import random
    import urllib.error
    import urllib.request

    from collections import deque

    rng = random.Random() if _rng is None else _rng
    stripped = url.rstrip("/")
    if not (stripped.endswith("/.events")
            or stripped.endswith("/events")):
        url = stripped + "/.events"
    seen: set = set()
    order: deque = deque()
    seen_limit = 4096  # > the flight ring bound: full replay coverage
    ended = False
    failures = 0
    while True:
        fresh = 0
        try:
            with urllib.request.urlopen(url) as resp:
                for raw in resp:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line.startswith("data:"):
                        continue  # keep-alive / drop-count comments
                    payload = line[len("data:"):].strip()
                    try:
                        ev = json.loads(payload)
                    except json.JSONDecodeError:
                        continue
                    key = json.dumps(ev, sort_keys=True, default=str)
                    if key in seen:
                        continue  # reconnect backlog replay
                    seen.add(key)
                    order.append(key)
                    if len(order) > seen_limit:
                        seen.discard(order.popleft())
                    fresh += 1
                    failures = 0
                    if ev.get("ev") in ("done", "error"):
                        ended = True
                    yield ev
            # clean close: finished run/replay, or a server going away
            if ended or not reconnect or fresh == 0:
                return
        except (OSError, urllib.error.URLError,
                http.client.HTTPException):
            if ended or not reconnect:
                return
            failures += 1
            if failures > retries:
                return
        delay = min(max_delay, base_delay * (2 ** max(failures - 1, 0)))
        _sleep(delay * (0.5 + rng.random() / 2))  # jittered backoff


def attach(checker, out=None, interval: float = 0.0,
           poll: float = 0.05) -> Console:
    """Subscribe a :class:`Console` to a live checker and render until
    the run completes (in-process mode). Returns the console (its
    ``rendered_*`` counters are what the tests assert on)."""
    import queue as _queue

    console = Console(out=out, interval=interval)
    q: "_queue.Queue" = _queue.Queue()
    checker.subscribe(q.put)
    checker._start_background()
    while True:
        try:
            console.feed(q.get(timeout=poll))
        except _queue.Empty:
            if checker.is_done():
                break
    while True:  # drain what landed between the last get and is_done
        try:
            console.feed(q.get_nowait())
        except _queue.Empty:
            break
    return console


def main(argv) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    once = "--once" in argv
    interval = 0.5
    if "--interval" in argv:
        interval = float(argv[argv.index("--interval") + 1])
    if "--url" in argv:
        source = follow_url(argv[argv.index("--url") + 1])
    else:
        paths = [a for a in argv if not a.startswith("--")]
        if not paths:
            print("watch.py: need a trace path or --url",
                  file=sys.stderr)
            return 2
        source = follow_file(paths[0], follow=not once)
    console = Console(interval=0.0 if once else interval)
    try:
        for ev in source:
            console.feed(ev)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
