"""Launch a multi-host (multi-process) sharded checking run.

Coordinator mode (no ``STPU_RANK`` in the environment):

    python tools/mesh_launch.py --procs 2 --devices-per-proc 2 \\
        --model twopc --args 3 --out /tmp/fleet [--capacity 4096]
        [--fmax 64] [--chunk-steps 2] [--target N] [--save]
        [--resume CKPT] [--timeout S]

spawns ``--procs`` copies of itself as fleet ranks (CPU-forced with
``--devices-per-proc`` virtual devices each — the ``dryrun_multichip``
recipe, per process), watches them with abort fan-out, and prints rank
0's ``result.json`` as one JSON line on stdout. Worker mode (launched
by the coordinator; identity in ``STPU_*`` env vars) bootstraps
``jax.distributed``, builds the host×device fleet mesh, and runs the
named ``MODEL_REGISTRY`` model SPMD across the GLOBAL mesh — the
fingerprint all-to-all exchange spans DCN between the processes.

Artifacts (all under ``--out``): rank 0 owns ``result.json`` (unique
count, sha256 fingerprint digest, discoveries, hosts/procs/shards),
``trace.jsonl``, and — with ``--save`` — ``checkpoint.npz`` (the
shard-agnostic format: resumable on ANY mesh, including a single
process); every rank writes ``rank<k>.log`` / ``rank<k>.ready``; the
coordinator writes ``fleet.jsonl`` (``host_join`` per rank +
``mesh_init``, rendered by ``tools/trace_report.py`` as ``fleet:``).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def parse_args(argv):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--devices-per-proc", type=int, default=2)
    ap.add_argument("--model", default="twopc")
    ap.add_argument("--args", nargs="*", type=int, default=[3])
    ap.add_argument("--out", required=True)
    ap.add_argument("--capacity", type=int, default=1 << 12)
    ap.add_argument("--fmax", type=int, default=64)
    ap.add_argument("--chunk-steps", type=int, default=2)
    ap.add_argument("--target", type=int, default=None)
    ap.add_argument("--save", action="store_true",
                    help="write a resume_from-loadable checkpoint at "
                         "the end (pair with --target to checkpoint "
                         "mid-search)")
    ap.add_argument("--resume", default=None,
                    help="resume from a checkpoint (any mesh width "
                         "wrote it)")
    ap.add_argument("--timeout", type=float, default=600.0)
    return ap.parse_args(argv)


def worker_main(args, ctx) -> int:
    """One rank: bootstrap is done (``ctx``); build the global mesh,
    run the model, land rank-0 artifacts."""
    import jax

    from stateright_tpu.cluster.mesh import fleet_mesh
    from stateright_tpu.service.jobs import build_model

    rank = ctx.rank
    out = args.out
    mesh = fleet_mesh("shards")
    from stateright_tpu.cluster.launch import write_ready_marker
    write_ready_marker(
        out, rank,
        local_devices=len(jax.local_devices()),
        global_devices=len(jax.devices()),
        shards=int(mesh.shape["shards"]))

    model = build_model(args.model, list(args.args), {})
    builder = (model.checker()
               .tpu_options(race=False, mesh=mesh,
                            capacity=args.capacity, fmax=args.fmax,
                            chunk_steps=args.chunk_steps))
    if rank == 0:
        builder.tpu_options(trace=os.path.join(out, "trace.jsonl"))
    if args.save:
        builder.tpu_options(resumable=True)
    if args.target:
        builder.target_state_count(args.target)
    if args.resume:
        builder.resume_from(args.resume)
    t0 = time.perf_counter()
    checker = builder.spawn_tpu().join()
    secs = time.perf_counter() - t0
    # COLLECTIVE pulls (mirror, frontier): every rank must take them,
    # in the same order — only the file writes are rank-0-owned
    fps = sorted(int(f) for f in checker.generated_fingerprints())
    digest = hashlib.sha256(
        "\n".join(map(str, fps)).encode()).hexdigest()
    if args.save:
        # the checkpoint save pulls nothing sharded (the resumable
        # frontier was pulled collectively during the run), but every
        # rank writing keeps the host loops symmetric anyway; rank 0's
        # name is the canonical one
        name = ("checkpoint.npz" if rank == 0
                else f"rank{rank}.checkpoint.npz")
        checker.save(os.path.join(out, name))
    if rank == 0:
        prof = checker.profile()
        result = {
            "model": args.model,
            "args": list(args.args),
            "unique": checker.unique_state_count(),
            "state_count": checker.state_count(),
            "fingerprints_sha256": digest,
            "discoveries": sorted(checker.discoveries()),
            "secs": round(secs, 4),
            "uniq_per_s": round(len(fps) / max(secs, 1e-9), 1),
            "procs": int(jax.process_count()),
            "hosts": int(prof.get("hosts", 1)),
            "shards": int(mesh.shape["shards"]),
            "resumed": bool(args.resume),
        }
        tmp = os.path.join(out, "result.json.tmp")
        with open(tmp, "w") as f:
            json.dump(result, f)
        os.replace(tmp, os.path.join(out, "result.json"))
    return 0


def coordinator_main(args) -> int:
    from stateright_tpu.cluster.launch import launch_fleet, pick_port
    from stateright_tpu.obs import make_trace

    out = args.out
    os.makedirs(out, exist_ok=True)
    trace = make_trace(os.path.join(out, "fleet.jsonl"),
                       engine="fleet")
    coordinator = f"127.0.0.1:{pick_port()}"
    cmd = [sys.executable, os.path.abspath(__file__)] + [
        a for a in sys.argv[1:]]
    t0 = time.perf_counter()
    res = launch_fleet(cmd, args.procs,
                       local_devices=args.devices_per_proc, cpu=True,
                       coordinator=coordinator, out_dir=out,
                       timeout=args.timeout, trace=trace)
    result_path = os.path.join(out, "result.json")
    if res.ok and os.path.isfile(result_path):
        with open(result_path) as f:
            result = json.load(f)
        trace.emit("mesh_init", shards=result.get("shards"),
                   hosts=result.get("hosts"),
                   procs=result.get("procs"),
                   wall=round(time.perf_counter() - t0, 4))
        trace.close()
        print(json.dumps(result))
        return 0
    trace.close()
    detail = res.aborted or f"returncodes={res.returncodes}"
    print(json.dumps({"error": f"fleet failed: {detail}",
                      "returncodes": res.returncodes}))
    for rank in range(args.procs):
        tail = res.tail(rank)
        if tail:
            sys.stderr.write(f"--- rank {rank} log tail ---\n{tail}\n")
    return 1


def main(argv) -> int:
    args = parse_args(argv)
    from stateright_tpu.cluster.mesh import init_from_env
    ctx = init_from_env()
    if ctx is not None:
        return worker_main(args, ctx)
    return coordinator_main(args)


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
